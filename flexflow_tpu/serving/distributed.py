"""Pod-scale serving placement: the (dp, tp) serving mesh and the
host-partitioned slot/page ownership map.

`optimize_serving` (search/auto.py) picks a decode-optimal (data, model)
mesh — but until `FFModel.compile_for_serving` existed the engine never
executed it: serving inherited whatever sharding the *training* strategy
compiled, on one process's mesh. This module is the missing application
layer, the Orca / FlexFlow-Serve distributed posture on the XLA-native
runtime:

* `build_serving_mesh` builds the (dp, tp) mesh through
  `runtime/multihost.global_mesh` so the outer "data" axis rides DCN
  (crosses hosts) and the inner "model" axis stays on ICI — decode's
  per-token all-reduce over tensor-parallel heads cannot tolerate DCN
  latency, page traffic on the data axis can.
* `ServingPlacement` carries the mesh plus the HOST partition: host h
  owns a contiguous block of request slots and KV pages, mirroring the
  device sharding of pool dim 0 on the "data" axis (NamedSharding
  slices dim 0 contiguously, so device shard boundaries and host
  ownership boundaries coincide). Block tables stay host-local numpy;
  batches are assembled into global arrays through
  `multihost.place_array` (the `place_batch` core).

The degenerate placement (dp = tp = num_hosts = 1) is byte-identical to
the pre-existing single-host engine: one mesh device, fully-replicated
specs, a single host owning every slot and page.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

SERVING_AXES = ("data", "model")

# how the executed mesh was chosen — recorded in exported strategy docs
# so the explain path cannot report a mesh the runtime ignored
MESH_SOURCES = ("flag", "searched", "inherited")


def parse_serve_mesh(text: str) -> Optional[Tuple[int, int]]:
    """Parse a ``--serve-mesh dp,tp`` flag value ('' -> None)."""
    if not text:
        return None
    parts = [p.strip() for p in str(text).split(",")]
    if len(parts) != 2:
        raise ValueError(
            f"--serve-mesh expects 'dp,tp' (got {text!r})"
        )
    try:
        dp, tp = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"--serve-mesh expects two integers 'dp,tp' (got {text!r})"
        )
    if dp < 1 or tp < 1:
        raise ValueError(f"--serve-mesh sizes must be >= 1 (got {text!r})")
    return dp, tp


def build_serving_mesh(dp: int, tp: int):
    """The (data=dp, model=tp) serving mesh over the first dp*tp devices.
    Outer axis on DCN, inner on ICI — see module docstring. Serving may
    use a subset of the machine (the search enumerates divisor counts),
    so the device list is sliced to exactly dp*tp before
    `create_device_mesh` (which requires an exact product)."""
    import jax

    from flexflow_tpu.runtime import multihost

    need = dp * tp
    devices = jax.devices()
    if need > len(devices):
        raise ValueError(
            f"serving mesh (data={dp}, model={tp}) needs {need} devices, "
            f"machine has {len(devices)}"
        )
    return multihost.global_mesh(
        SERVING_AXES, (dp, tp), devices=devices[:need]
    )


def resolve_num_hosts(serve_hosts: int, dp: int) -> int:
    """How many host partitions the scheduler runs. An explicit
    ``--serve-hosts`` wins (simulated hosts on one process — the CPU
    testing posture); otherwise a real multi-process run uses
    `jax.process_count()`; otherwise one host partition per data-axis
    shard (each dp shard's pages live with one host's devices)."""
    if serve_hosts and serve_hosts > 0:
        return int(serve_hosts)
    import jax

    if jax.process_count() > 1:
        return jax.process_count()
    return max(1, int(dp))


@dataclasses.dataclass(frozen=True)
class ServingPlacement:
    """The applied serving mesh + host ownership map.

    `mesh_source` records how (dp, tp) was chosen: "flag"
    (--serve-mesh), "searched" (`search_serving_strategy` winner,
    applied), or "inherited" (no serving mesh — the engine keeps the
    training strategy's sharding; only recorded in docs, a real
    placement is never built inherited)."""

    mesh: object  # jax.sharding.Mesh
    dp: int
    tp: int
    num_hosts: int
    num_heads: int
    mesh_source: str = "flag"

    def kv_sharding(self):
        """NamedSharding for both KV pool layouts. Paged pools are
        (num_pages, page_size, heads, head_dim) — pages follow the data
        axis (host-owned blocks), heads the model axis. Slot pools are
        (max_seqs, max_len, heads, head_dim) — same spec, slots on the
        data axis."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(
            self.mesh, PartitionSpec("data", None, "model", None)
        )

    def scale_sharding(self):
        """Quantized-pool scale tables are (num_pages, num_heads)."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec("data", "model"))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def head_sharding(self, heads_dim: int, ndim: int):
        """NamedSharding partitioning axis `heads_dim` of an
        `ndim`-rank weight over the model axis (attention projection
        weights: heads is dim 1 of wq/wk/wv, dim 0 of wo and the
        q/k/v biases)."""
        from jax.sharding import NamedSharding, PartitionSpec

        spec = [None] * ndim
        spec[heads_dim] = "model"
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def validate_geometry(self, max_seqs: int, num_pages: int) -> None:
        """Reject cache geometries the host partition cannot split
        evenly — the runtime mirror of fxlint's FX311/FX312 doc rules."""
        if self.tp >= 1 and self.num_heads % self.tp:
            raise ValueError(
                f"serving mesh model={self.tp} does not divide "
                f"num_heads={self.num_heads}"
            )
        for name, n in (("max_seqs", max_seqs), ("num_pages", num_pages)):
            if n % self.num_hosts:
                raise ValueError(
                    f"serving placement: {name}={n} is not divisible by "
                    f"num_hosts={self.num_hosts} — each host must own an "
                    "equal block"
                )
            if n % self.dp:
                raise ValueError(
                    f"serving placement: {name}={n} is not divisible by "
                    f"data={self.dp} — pool dim 0 shards on the data axis"
                )

    def describe(self) -> str:
        return (
            f"serving placement mesh(data={self.dp}, model={self.tp}) "
            f"[{self.mesh_source}], {self.num_hosts} host partition(s), "
            f"{self.num_heads} heads"
        )

    def to_doc(
        self,
        max_seqs: Optional[int] = None,
        num_pages: Optional[int] = None,
    ) -> dict:
        """The exported serving-placement document — validated by fxlint
        `strategy-validate` (FX310-FX312, strategy_check.py)."""
        doc = {
            "version": 1,
            "kind": "serving",
            "mesh_axes": list(SERVING_AXES),
            "mesh_sizes": [self.dp, self.tp],
            "dp": self.dp,
            "tp": self.tp,
            "num_hosts": self.num_hosts,
            "num_heads": self.num_heads,
            "mesh_source": self.mesh_source,
        }
        if num_pages is not None:
            doc["page_pool"] = {
                "num_pages": int(num_pages),
                "pages_per_host": int(num_pages) // self.num_hosts,
            }
        if max_seqs is not None:
            doc["slots"] = {
                "max_seqs": int(max_seqs),
                "slots_per_host": int(max_seqs) // self.num_hosts,
            }
        return doc


def build_placement(
    model,
    dp: int,
    tp: int,
    num_hosts: Optional[int] = None,
    mesh_source: str = "flag",
) -> ServingPlacement:
    """Build the serving mesh and host partition for a compiled model.
    Validates tp against the graph's attention head count before any
    device work (the search already prunes non-dividing tp, but a
    --serve-mesh flag can ask for anything)."""
    from flexflow_tpu.search.auto import _serving_cache_geometry

    _, heads, _ = _serving_cache_geometry(model.graph)
    if tp > 1 and heads % tp:
        raise ValueError(
            f"serving mesh model={tp} does not divide the graph's "
            f"num_heads={heads}"
        )
    mesh = build_serving_mesh(dp, tp)
    hosts = resolve_num_hosts(0 if num_hosts is None else num_hosts, dp)
    return ServingPlacement(
        mesh=mesh,
        dp=dp,
        tp=tp,
        num_hosts=hosts,
        num_heads=heads,
        mesh_source=mesh_source,
    )
