"""Multi-tenant serving: paged multi-LoRA adapters, weighted-fair
priority scheduling, and per-class SLO telemetry.

Three layers, one subsystem:

- :mod:`adapters` — a paged ``AdapterPool`` (the PagedKVCache's sibling
  allocator) holding rank-r LoRA deltas for the attention projections,
  gathered per batch row into the engine's dense projections.
- :mod:`fairness` — ``PriorityClass`` config and the deficit round-robin
  machinery that makes admission and the PR 10 token-budget planner's
  chunk grants weighted-fair across classes.
- :mod:`slo` — per-class rolling TTFT/ITL windows and SLO-violation
  counters (PR 8's monitors generalized with labels).
"""

from flexflow_tpu.serving.tenancy.adapters import (  # noqa: F401
    AdapterPool,
    AdapterPoolExhausted,
    AdapterPoolSpec,
    adapter_rows,
    apply_adapter_out,
    apply_adapter_qkv,
    make_lora_weights,
)
from flexflow_tpu.serving.tenancy.fairness import (  # noqa: F401
    DeficitRoundRobin,
    PriorityClass,
    parse_classes,
)
from flexflow_tpu.serving.tenancy.slo import (  # noqa: F401
    build_class_monitors,
)
