"""Per-class SLO monitoring — PR 8's rolling monitors, generalized.

One :class:`~flexflow_tpu.telemetry.slo.SLOMonitor` per priority class,
labelled ``{"class": name}``: the unlabelled monitor the Telemetry
object already owns stays the fleet-wide aggregate, and each class gets
its own rolling TTFT/ITL windows, violation counters
(``serve_slo_violations_total{class="gold",slo="ttft"}``) and
percentile gauges riding the same registry and the same JSONL rows.
Thresholds come from the class config (``PriorityClass.slo_ttft_ms`` /
``slo_itl_ms``; 0 = observe-only)."""

from typing import Dict, Mapping

from flexflow_tpu.serving.tenancy.fairness import PriorityClass
from flexflow_tpu.telemetry.slo import SLOMonitor


def build_class_monitors(
    registry,
    classes: Mapping[str, PriorityClass],
    window: int = 1024,
) -> Dict[str, SLOMonitor]:
    """{class name: labelled SLOMonitor} for every configured class."""
    return {
        name: SLOMonitor(
            registry,
            ttft_ms=cls.slo_ttft_ms,
            itl_ms=cls.slo_itl_ms,
            window=window,
            labels={"class": name},
        )
        for name, cls in classes.items()
    }


def class_slo_snapshot(monitors: Mapping[str, SLOMonitor]) -> Dict[str, dict]:
    """{class: monitor snapshot} — bench artifacts embed this so the
    per-class attainment gates read straight off the export."""
    return {name: mon.snapshot() for name, mon in monitors.items()}
