"""Weighted-fair scheduling across priority classes.

Two mechanisms share one :class:`DeficitRoundRobin` core:

- **Admission** (cost = 1 per request): instead of strict global FIFO,
  the scheduler serves per-class FIFO queues in deficit round-robin
  order, so a gold:4 / bronze:1 config admits ~4 gold requests per
  bronze under contention while bronze still admits every round —
  starvation-free by construction (every backlogged class's deficit
  grows by its quantum each round, so it affords a serve within
  ``ceil(cost / quantum)`` rounds).
- **Token grants** (cost = the chunk unit, up to chunk_size): the PR 10
  chunk planner's per-iteration grants are DRR serves, so prefill
  bandwidth under a token budget divides by class weight instead of
  admission order.

The DRR state is deliberately tiny and inspectable (``deficit`` is a
public dict) because the tests assert its conservation invariant
directly: after any serve sequence, every class's deficit sits in
``[0, quantum + max_cost)`` and idle classes forfeit theirs.

``select`` is PURE (commit-on-success): admission may discover the
chosen class's head cannot take a slot right now, in which case nothing
must have been charged — the caller only ``charge``s after the admit
actually lands.
"""

from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Sequence, Tuple

_EPS = 1e-9


@dataclass(frozen=True)
class PriorityClass:
    """One tenant class: scheduling weight plus optional per-class SLO
    targets (0 = no target; the class still gets labelled latency
    windows, just no violation counting)."""

    name: str
    weight: float = 1.0
    slo_ttft_ms: float = 0.0
    slo_itl_ms: float = 0.0

    def __post_init__(self):
        if not self.name:
            raise ValueError("priority class needs a name")
        if self.weight <= 0:
            raise ValueError(
                f"class {self.name!r}: weight must be > 0, "
                f"got {self.weight}"
            )
        if self.slo_ttft_ms < 0 or self.slo_itl_ms < 0:
            raise ValueError(
                f"class {self.name!r}: SLO targets must be >= 0"
            )


def parse_classes(spec: str) -> Dict[str, PriorityClass]:
    """Parse the ``--classes`` flag: ``name:weight[:ttft_ms[:itl_ms]]``
    entries, comma-separated — e.g. ``gold:4:200:50,bronze:1``. Config
    order is scheduling order (DRR visit order and the default class for
    requests that name none)."""
    classes: Dict[str, PriorityClass] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) > 4:
            raise ValueError(
                f"class entry {entry!r}: expected "
                "name:weight[:ttft_ms[:itl_ms]]"
            )
        name = parts[0].strip()
        if name in classes:
            raise ValueError(f"duplicate class {name!r}")
        try:
            weight = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
            ttft = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
            itl = float(parts[3]) if len(parts) > 3 and parts[3] else 0.0
        except ValueError:
            raise ValueError(f"class entry {entry!r}: non-numeric field")
        classes[name] = PriorityClass(
            name=name, weight=weight, slo_ttft_ms=ttft, slo_itl_ms=itl
        )
    if not classes:
        raise ValueError(f"no classes in spec {spec!r}")
    return classes


class DeficitRoundRobin:
    """Deficit round-robin over named classes.

    Each *round* credits every backlogged class ``quantum = unit *
    weight``; a class is served while its deficit affords the head
    cost. Rather than looping rounds imperatively, :meth:`select`
    computes for each backlogged class how many whole rounds it needs
    before it can afford its head (``ceil((cost - deficit) /
    quantum)``) and serves the minimum — ties break by visit order from
    the cursor, so equal-entitlement decisions are deterministic and
    chaos schedules replay exactly. :meth:`charge` then commits that
    serve: the skipped rounds' quanta accrue to EVERY backlogged class
    (they were entitled to them), the served class pays its cost, and
    the cursor parks on it (classic DRR keeps serving a class while its
    deficit lasts)."""

    def __init__(self, weights: Mapping[str, float], unit: float = 1.0):
        if not weights:
            raise ValueError("DeficitRoundRobin needs at least one class")
        self._order = list(weights)
        self.weights = {n: float(w) for n, w in weights.items()}
        for n, w in self.weights.items():
            if w <= 0:
                raise ValueError(f"class {n!r}: weight must be > 0, got {w}")
        if unit <= 0:
            raise ValueError(f"unit must be > 0, got {unit}")
        self.unit = float(unit)
        self.deficit: Dict[str, float] = {n: 0.0 for n in self._order}
        self._cursor = 0

    def quantum(self, name: str) -> float:
        return self.unit * self.weights[name]

    def _rounds(self, name: str, cost: float) -> int:
        """Whole rounds before `name` affords `cost` (0 = affords now)."""
        short = cost - self.deficit[name]
        if short <= _EPS:
            return 0
        q = self.quantum(name)
        return int(-(-(short - _EPS) // q))

    def select(
        self, costs: Mapping[str, float]
    ) -> Optional[Tuple[str, int]]:
        """PURE: the next DRR serve over the backlogged classes in
        ``costs`` ({class: its head's cost}) — returns (class, rounds
        the serve had to wait) or None when nothing is backlogged.
        State is untouched until :meth:`charge` commits."""
        best: Optional[Tuple[str, int]] = None
        n = len(self._order)
        for i in range(n):
            name = self._order[(self._cursor + i) % n]
            if name not in costs:
                continue
            r = self._rounds(name, float(costs[name]))
            if best is None or r < best[1]:
                best = (name, r)
        return best

    def charge(
        self,
        name: str,
        rounds: int,
        backlogged: Sequence[str],
        cost: float = 1.0,
    ) -> None:
        """Commit the serve :meth:`select` chose."""
        if rounds:
            for nm in self._order:
                if nm in backlogged:
                    self.deficit[nm] += rounds * self.quantum(nm)
        self.deficit[name] -= float(cost)
        self._cursor = self._order.index(name)

    def settle(self, backlogged: Sequence[str]) -> None:
        """Classic DRR bookkeeping between planning passes: a class with
        no backlog forfeits its carried deficit (credit must never
        accumulate while idle — that would let a silent class burst
        past its weight later)."""
        for nm in self._order:
            if nm not in backlogged:
                self.deficit[nm] = 0.0

    def check_invariants(self, max_cost: float = 1.0) -> None:
        """Deficit conservation: every class's deficit sits in
        ``(-eps, quantum + max_cost)`` — a serve only happens once
        affordable (floor) and rounds are minimal (ceiling)."""
        for nm in self._order:
            d = self.deficit[nm]
            hi = self.quantum(nm) + float(max_cost)
            if not (-_EPS <= d < hi + _EPS):
                raise AssertionError(
                    f"class {nm!r}: deficit {d} outside [0, {hi})"
                )
