"""Paged multi-LoRA adapter pool — the PagedKVCache's sibling allocator.

S-LoRA/Punica posture: one base model serves thousands of tenants by
keeping each tenant's rank-r LoRA factors for the attention projections
(Q/K/V/out) resident in pooled device arrays and gathering the right
pages per batch row inside the engine's jitted steps. Paging runs over
the RANK dimension: a pool page holds ``page_rank`` rank slices, an
adapter of rank r occupies ``ceil(r / page_rank)`` pages, and the delta
``(x @ A) @ B`` sums exactly over pages because a LoRA product is a sum
over rank slices.

Allocator discipline mirrors the KV pool deliberately: a free-page heap
(`heapq` over ``_free_adapter_pages``), per-page refcounts
(``_adapter_refcounts``: 1 for the load's ownership plus 1 per attached
slot), table writes (``adapter_tables``) only inside the blessed
helpers below, and ``check_invariants`` re-deriving every ledger from
the tables — fxlint FX110 holds the mutation surface to the blessed
set the same way FX106 does for the KV allocator.

Device layout per attention layer guid (``NP`` pool pages, ``pr`` =
page_rank, ``e`` = embed, ``h``/``d`` = heads/head_dim):

- ``a_q``/``a_k``/``a_v``: ``[NP+1, e, pr]``
- ``b_q``/``b_k``/``b_v``: ``[NP+1, pr, h, d]``
- ``a_o``: ``[NP+1, h, d, pr]``; ``b_o``: ``[NP+1, pr, e]``

Row ``NP`` is the permanent zero sentinel: unused table entries point
at it, so a sentinel gather contributes exactly 0.0 and rows without an
adapter stay bit-identical through the ``jnp.where`` select in
:func:`apply_adapter_qkv` / :func:`apply_adapter_out`. The pools are
rebound functionally on every load (fresh ``.at[page].set`` arrays), so
an in-flight dispatched step keeps the arrays it captured — loads and
unloads can never tear a step that is already on the device.
"""

import heapq
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from flexflow_tpu.ops.attention import lora_delta_out, lora_delta_qkv


class AdapterPoolExhausted(RuntimeError):
    """Raised when a load needs more adapter pages than the pool holds."""


_AB_NAMES = ("a_q", "b_q", "a_k", "b_k", "a_v", "b_v", "a_o", "b_o")


@dataclass(frozen=True)
class AdapterPoolSpec:
    """Geometry of one adapter pool (all attention layers share it)."""

    layer_guids: Tuple[int, ...]
    max_seqs: int
    embed_dim: int
    num_heads: int
    head_dim: int
    max_adapters: int
    max_rank: int
    page_rank: int
    num_pages: int

    @property
    def pages_per_adapter(self) -> int:
        return -(-self.max_rank // self.page_rank)

    def pages_for(self, rank: int) -> int:
        return -(-rank // self.page_rank)


def default_page_rank(max_rank: int) -> int:
    """Auto page sizing: small enough to pack mixed ranks without
    waste, capped at 4 rank slices per page (the KV pool's "page_size
    divides max_len" posture transplanted to rank)."""
    return max(1, min(int(max_rank), 4))


class AdapterPool:
    """Paged pool of LoRA adapter factors plus the slot→adapter map the
    engine snapshots at dispatch.

    Host ledgers (mutated ONLY inside the blessed helpers — fxlint
    FX110):

    - ``adapter_tables`` [max_adapters, pages_per_adapter] int32: the
      pages backing each loaded adapter, sentinel ``num_pages`` in
      unused entries.
    - ``_free_adapter_pages``: min-heap of free page ids (lowest-first
      pops keep allocation deterministic for replay).
    - ``_adapter_refcounts`` [num_pages] int32: 1 while an adapter owns
      the page, +1 per slot attached to that adapter.
    - ``slot_adapter`` [max_seqs] int32: the adapter each slot serves
      (-1 = base model).
    """

    def __init__(self, spec: AdapterPoolSpec, dtype=jnp.float32):
        if spec.max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1, got {spec.max_adapters}"
            )
        if spec.max_rank < 1:
            raise ValueError(f"max_rank must be >= 1, got {spec.max_rank}")
        if spec.page_rank < 1:
            raise ValueError(f"page_rank must be >= 1, got {spec.page_rank}")
        if spec.num_pages < spec.pages_per_adapter:
            raise ValueError(
                f"num_pages {spec.num_pages} cannot hold even one "
                f"max_rank adapter ({spec.pages_per_adapter} pages)"
            )
        self.spec = spec
        self.dtype = dtype
        P = spec.pages_per_adapter
        self.adapter_tables = np.full(
            (spec.max_adapters, P), spec.num_pages, dtype=np.int32
        )
        self._free_adapter_pages: List[int] = list(range(spec.num_pages))
        heapq.heapify(self._free_adapter_pages)
        self._adapter_refcounts = np.zeros(spec.num_pages, dtype=np.int32)
        self.slot_adapter = np.full(spec.max_seqs, -1, dtype=np.int32)
        self._loaded: Dict[int, int] = {}  # adapter_id -> rank
        self.loads = 0
        self.unloads = 0
        self.attaches = 0
        self.detaches = 0
        e, h, d, pr = spec.embed_dim, spec.num_heads, spec.head_dim, spec.page_rank
        rows = spec.num_pages + 1  # + the permanent zero-sentinel row
        pools: Dict[int, Dict[str, jnp.ndarray]] = {}
        for g in spec.layer_guids:
            pools[g] = {
                "a_q": jnp.zeros((rows, e, pr), dtype=dtype),
                "b_q": jnp.zeros((rows, pr, h, d), dtype=dtype),
                "a_k": jnp.zeros((rows, e, pr), dtype=dtype),
                "b_k": jnp.zeros((rows, pr, h, d), dtype=dtype),
                "a_v": jnp.zeros((rows, e, pr), dtype=dtype),
                "b_v": jnp.zeros((rows, pr, h, d), dtype=dtype),
                "a_o": jnp.zeros((rows, h, d, pr), dtype=dtype),
                "b_o": jnp.zeros((rows, pr, e), dtype=dtype),
            }
        self._pools = pools

    # -- construction --------------------------------------------------------

    @classmethod
    def from_model(
        cls,
        model,
        max_seqs: int,
        max_adapters: int = 8,
        max_rank: int = 8,
        page_rank: int = 0,
        num_pages: int = 0,
        dtype=jnp.float32,
    ) -> "AdapterPool":
        """Build a pool sized for a compiled model: geometry comes from
        the same `_derive_geometry` the KV cache uses, so the two
        sibling allocators can never disagree on the attention shape."""
        from flexflow_tpu.serving.kv_cache import _derive_geometry

        guids, heads, head_dim, _head_axis, _executor = _derive_geometry(
            model
        )
        pr = page_rank if page_rank else default_page_rank(max_rank)
        per = -(-max_rank // pr)
        spec = AdapterPoolSpec(
            layer_guids=tuple(guids),
            max_seqs=int(max_seqs),
            embed_dim=heads * head_dim,
            num_heads=heads,
            head_dim=head_dim,
            max_adapters=int(max_adapters),
            max_rank=int(max_rank),
            page_rank=int(pr),
            num_pages=int(num_pages) if num_pages else int(max_adapters) * per,
        )
        return cls(spec, dtype=dtype)

    # -- blessed mutators (fxlint FX110) -------------------------------------

    def _pop_free_adapter_page(self) -> int:
        """The ONE path pages leave the free heap by."""
        if not self._free_adapter_pages:
            raise AdapterPoolExhausted(
                f"adapter pool dry: {self.spec.num_pages} pages all owned"
            )
        return heapq.heappop(self._free_adapter_pages)

    def _install_adapter_page(self, adapter_id: int, pi: int, page: int):
        """Bind a popped page into an adapter's table, refcount 1 (the
        load's own reference)."""
        self.adapter_tables[adapter_id, pi] = page
        self._adapter_refcounts[page] = 1

    def _free_adapter_page(self, adapter_id: int, pi: int) -> None:
        """Unbind one table entry back to the sentinel and return the
        page to the heap. Only legal at refcount 1 — unload refuses
        while any slot still holds a reference."""
        page = int(self.adapter_tables[adapter_id, pi])
        self.adapter_tables[adapter_id, pi] = self.spec.num_pages
        self._adapter_refcounts[page] = 0
        heapq.heappush(self._free_adapter_pages, page)

    def load(self, adapter_id: int, weights, scale: float = 1.0) -> None:
        """Load one adapter's factors into pooled pages.

        ``weights``: {layer_guid: {"a_q": [e, r], "b_q": [r, e], ...}}
        (2-D host matrices; ``e`` for the b/out factors is the flattened
        head space ``h*d``). Rank is inferred from the factors, alpha/
        scale folds into B here — the gather path never rescales. Pages
        are fully overwritten (final page zero-padded past the rank), so
        a recycled page can never leak a previous tenant's factors."""
        aid = int(adapter_id)
        if not 0 <= aid < self.spec.max_adapters:
            raise ValueError(
                f"adapter_id {aid} outside [0, {self.spec.max_adapters})"
            )
        if aid in self._loaded:
            raise ValueError(f"adapter {aid} already loaded (unload first)")
        missing = [g for g in self.spec.layer_guids if g not in weights]
        if missing:
            raise ValueError(f"weights missing attention layers {missing}")
        rank = int(np.asarray(weights[self.spec.layer_guids[0]]["a_q"]).shape[1])
        if not 1 <= rank <= self.spec.max_rank:
            raise ValueError(
                f"rank {rank} outside [1, {self.spec.max_rank}]"
            )
        e, h, d = self.spec.embed_dim, self.spec.num_heads, self.spec.head_dim
        pr = self.spec.page_rank
        n = self.spec.pages_for(rank)
        if len(self._free_adapter_pages) < n:
            raise AdapterPoolExhausted(
                f"adapter {aid} needs {n} pages, "
                f"{len(self._free_adapter_pages)} free"
            )
        pages = [self._pop_free_adapter_page() for _ in range(n)]
        for pi, page in enumerate(pages):
            self._install_adapter_page(aid, pi, page)
        pools = dict(self._pools)
        for g in self.spec.layer_guids:
            mats = {
                k: np.asarray(weights[g][k], dtype=np.float32)
                for k in _AB_NAMES
            }
            for k in ("a_q", "a_k", "a_v", "a_o"):
                if mats[k].shape != (e, rank):
                    raise ValueError(
                        f"layer {g} {k}: expected {(e, rank)}, "
                        f"got {mats[k].shape}"
                    )
            for k in ("b_q", "b_k", "b_v", "b_o"):
                if mats[k].shape != (rank, e):
                    raise ValueError(
                        f"layer {g} {k}: expected {(rank, e)}, "
                        f"got {mats[k].shape}"
                    )
                mats[k] = mats[k] * float(scale)
            pool = dict(pools[g])
            for pi, page in enumerate(pages):
                lo, hi = pi * pr, min(rank, (pi + 1) * pr)
                w = hi - lo
                blk = {
                    k: np.zeros(tuple(pool[k].shape[1:]), dtype=np.float32)
                    for k in _AB_NAMES
                }
                for k in ("a_q", "a_k", "a_v"):
                    blk[k][:, :w] = mats[k][:, lo:hi]
                for k in ("b_q", "b_k", "b_v"):
                    blk[k][:w] = mats[k][lo:hi].reshape(w, h, d)
                blk["a_o"][:, :, :w] = mats["a_o"][:, lo:hi].reshape(h, d, w)
                blk["b_o"][:w] = mats["b_o"][lo:hi]
                for k in _AB_NAMES:
                    pool[k] = pool[k].at[page].set(
                        jnp.asarray(blk[k], dtype=self.dtype)
                    )
            pools[g] = pool
        self._pools = pools
        self._loaded[aid] = rank
        self.loads += 1

    def unload(self, adapter_id: int) -> None:
        """Return an adapter's pages to the pool. Refuses while any slot
        is attached — the engine may still gather those pages."""
        aid = int(adapter_id)
        if aid not in self._loaded:
            raise ValueError(f"adapter {aid} is not loaded")
        n = self.spec.pages_for(self._loaded[aid])
        pages = [int(self.adapter_tables[aid, pi]) for pi in range(n)]
        if any(self._adapter_refcounts[p] != 1 for p in pages):
            attached = int((self.slot_adapter == aid).sum())
            raise RuntimeError(
                f"adapter {aid} still attached to {attached} slot(s)"
            )
        for pi in range(n):
            self._free_adapter_page(aid, pi)
        self._loaded.pop(aid)
        self.unloads += 1

    def attach(self, slot: int, adapter_id: int) -> None:
        """Point a slot at an adapter (-1 = base model) and pin its
        pages. The scheduler calls this at admission, before the slot's
        first prefill dispatch."""
        s = int(slot)
        if not 0 <= s < self.spec.max_seqs:
            raise ValueError(f"slot {s} outside [0, {self.spec.max_seqs})")
        if self.slot_adapter[s] != -1:
            raise RuntimeError(
                f"slot {s} already attached to adapter "
                f"{int(self.slot_adapter[s])} (detach first)"
            )
        aid = int(adapter_id)
        if aid == -1:
            return
        if aid not in self._loaded:
            raise ValueError(f"adapter {aid} is not loaded")
        self.slot_adapter[s] = aid
        n = self.spec.pages_for(self._loaded[aid])
        for pi in range(n):
            self._adapter_refcounts[self.adapter_tables[aid, pi]] += 1
        self.attaches += 1

    def detach(self, slot: int) -> None:
        """Release a slot's adapter reference (idempotent for base-model
        slots). The scheduler calls this wherever the slot frees —
        finalize, preemption, stage-out, evacuation."""
        s = int(slot)
        aid = int(self.slot_adapter[s])
        if aid == -1:
            return
        self.slot_adapter[s] = -1
        n = self.spec.pages_for(self._loaded[aid])
        for pi in range(n):
            self._adapter_refcounts[self.adapter_tables[aid, pi]] -= 1
        self.detaches += 1

    # -- dispatch-side views -------------------------------------------------

    @property
    def device_pools(self) -> Dict[int, Dict[str, jnp.ndarray]]:
        return self._pools

    @property
    def loaded(self) -> Dict[int, int]:
        """{adapter_id: rank} of the currently loaded adapters."""
        return dict(self._loaded)

    def slot_tables(self) -> Tuple[np.ndarray, np.ndarray]:
        """(tbl [max_seqs, P] int32, has [max_seqs] bool) for the
        slot-indexed steps (decode/verify/multistep/chunk). Fresh host
        arrays — the engine snapshots them at dispatch, so the step
        rides its own copy (FX103 discipline)."""
        has = self.slot_adapter >= 0
        tbl = np.full(
            (self.spec.max_seqs, self.spec.pages_per_adapter),
            self.spec.num_pages,
            dtype=np.int32,
        )
        rows = np.nonzero(has)[0]
        if rows.size:
            tbl[rows] = self.adapter_tables[self.slot_adapter[rows]]
        return tbl, has.copy()

    def row_tables(
        self, slots: Sequence[int], rows: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(tbl [rows, P], has [rows]) aligned to a prefill batch whose
        row i serves slot ``slots[i]`` (pad rows past len(slots) get the
        sentinel/base row)."""
        tbl = np.full(
            (rows, self.spec.pages_per_adapter),
            self.spec.num_pages,
            dtype=np.int32,
        )
        has = np.zeros(rows, dtype=bool)
        for i, s in enumerate(slots):
            aid = int(self.slot_adapter[int(s)])
            if aid >= 0:
                tbl[i] = self.adapter_tables[aid]
                has[i] = True
        return tbl, has

    # -- invariants / telemetry ----------------------------------------------

    def check_invariants(self) -> None:
        """Re-derive every ledger from the tables (the KV allocator's
        debug contract): page ownership is disjoint, refcounts equal
        1 + attached slots, the free heap is exactly the unowned pages,
        conservation holds, and the sentinel pool row is still zero."""
        spec = self.spec
        owned: Dict[int, Tuple[int, int]] = {}
        for aid in range(spec.max_adapters):
            rank = self._loaded.get(aid)
            n = spec.pages_for(rank) if rank else 0
            for pi in range(spec.pages_per_adapter):
                page = int(self.adapter_tables[aid, pi])
                if pi < n:
                    if not 0 <= page < spec.num_pages:
                        raise AssertionError(
                            f"adapter {aid} page {pi} out of range: {page}"
                        )
                    if page in owned:
                        raise AssertionError(
                            f"page {page} owned twice: {owned[page]} and "
                            f"({aid}, {pi})"
                        )
                    owned[page] = (aid, pi)
                elif page != spec.num_pages:
                    raise AssertionError(
                        f"adapter {aid} unused entry {pi} not sentinel: "
                        f"{page}"
                    )
        expected = np.zeros(spec.num_pages, dtype=np.int32)
        for page in owned:
            expected[page] = 1
        for s in range(spec.max_seqs):
            aid = int(self.slot_adapter[s])
            if aid == -1:
                continue
            if aid not in self._loaded:
                raise AssertionError(
                    f"slot {s} attached to unloaded adapter {aid}"
                )
            for pi in range(spec.pages_for(self._loaded[aid])):
                expected[self.adapter_tables[aid, pi]] += 1
        if not np.array_equal(self._adapter_refcounts, expected):
            bad = np.nonzero(self._adapter_refcounts != expected)[0]
            raise AssertionError(
                f"adapter refcounts diverge at pages {bad.tolist()}: "
                f"have {self._adapter_refcounts[bad].tolist()}, "
                f"derived {expected[bad].tolist()}"
            )
        free = set(self._free_adapter_pages)
        if len(free) != len(self._free_adapter_pages):
            raise AssertionError("duplicate pages in the adapter free heap")
        if free & set(owned):
            raise AssertionError(
                f"pages both owned and free: {sorted(free & set(owned))}"
            )
        if len(owned) + len(free) != spec.num_pages:
            raise AssertionError(
                f"adapter page conservation broken: {len(owned)} owned + "
                f"{len(free)} free != {spec.num_pages}"
            )
        for g in spec.layer_guids:
            for k in _AB_NAMES:
                row = np.asarray(self._pools[g][k][spec.num_pages])
                if row.any():
                    raise AssertionError(
                        f"layer {g} {k}: sentinel row is not zero"
                    )

    def telemetry_gauges(self) -> Dict[str, float]:
        free = len(self._free_adapter_pages)
        return {
            "adapters_loaded": float(len(self._loaded)),
            "adapter_pages_live": float(self.spec.num_pages - free),
            "adapter_pages_free": float(free),
            "adapter_slots_attached": float(
                int((self.slot_adapter >= 0).sum())
            ),
        }

    def telemetry_counters(self) -> Dict[str, int]:
        return {
            "adapter_loads_total": self.loads,
            "adapter_unloads_total": self.unloads,
            "adapter_attaches_total": self.attaches,
            "adapter_detaches_total": self.detaches,
        }


# -- jit-side application (called inside the engine's traced steps) ----------


def apply_adapter_qkv(x, q, k, v, ad, guid):
    """Fuse the per-row LoRA deltas into the Q/K/V projections right
    after ``mha_project_qkv``. ``ad`` is None (no pool — the traced HLO
    is byte-for-byte today's engine) or ``(tbl, has, pools)``; rows with
    ``has`` False take the UNMODIFIED q/k/v elements through the select,
    so base-model rows stay bit-identical whether or not a pool rides
    the step. K/V deltas land BEFORE the cache writes — the paged/Pallas
    attention cores then read adapted history with no kernel change."""
    if ad is None:
        return q, k, v
    tbl, has, pools = ad
    p = pools[guid]
    dq, dk, dv = lora_delta_qkv(
        x, tbl, p["a_q"], p["b_q"], p["a_k"], p["b_k"], p["a_v"], p["b_v"]
    )
    sel = has[:, None, None, None]
    q = jnp.where(sel, (q.astype(jnp.float32) + dq).astype(q.dtype), q)
    k = jnp.where(sel, (k.astype(jnp.float32) + dk).astype(k.dtype), k)
    v = jnp.where(sel, (v.astype(jnp.float32) + dv).astype(v.dtype), v)
    return q, k, v


def apply_adapter_out(attn, y, ad, guid):
    """Fuse the output-projection LoRA delta after ``mha_project_out`` —
    the post-kernel epilogue: the attention core (dense or Pallas)
    already ran, untouched."""
    if ad is None:
        return y
    tbl, has, pools = ad
    p = pools[guid]
    dy = lora_delta_out(attn, tbl, p["a_o"], p["b_o"])
    return jnp.where(
        has[:, None, None], (y.astype(jnp.float32) + dy).astype(y.dtype), y
    )


def adapter_rows(ad, slot_ids):
    """Gather a slot-indexed ``ad`` down to a compacted batch (the
    chunked-prefill impls, whose row i serves slot ``slot_ids[i]``)."""
    if ad is None:
        return None
    tbl, has, pools = ad
    return tbl[slot_ids], has[slot_ids], pools


# -- test/bench weight helper ------------------------------------------------


def make_lora_weights(spec: AdapterPoolSpec, rank: int, seed: int = 0):
    """Deterministic random LoRA factors shaped for :meth:`AdapterPool
    .load` — the tests' and bench's stand-in for real fine-tunes."""
    rng = np.random.default_rng(seed)
    e = spec.embed_dim
    weights = {}
    for g in spec.layer_guids:
        weights[g] = {
            k: rng.standard_normal((e, rank)).astype(np.float32) * 0.1
            if k.startswith("a_")
            else rng.standard_normal((rank, e)).astype(np.float32) * 0.1
            for k in _AB_NAMES
        }
    return weights
