"""flexflow_tpu.serving: continuous-batching inference on the trained PCG.

The training side of this rebuild compiles a PCG into one jitted train
step; this package is the inference mirror (upstream FlexFlow grew the
same subsystem as FlexFlow Serve): a block-paged KV cache with a
host-side page allocator and block tables (kv_cache; the PR-1
slot-contiguous layout remains as the kv_layout="slot" baseline),
prefill/decode step functions that re-execute the
compiled graph with a cache-aware attention hook (engine), an Orca-style
iteration-level scheduler with per-request fault isolation, deadlines/
cancellation, and optimistic-admission preemption-by-recompute
(scheduler), a seeded deterministic fault-injection harness (faults),
and the `FFModel.generate` / ServeConfig surface (api). The decode
regime also has its own cost family in search/cost_model.py so the
auto-parallel search can pick a serving strategy (TP over heads at
small batch) distinct from the training one. Observability lives in
its own package (flexflow_tpu.telemetry — metrics registry, Chrome
trace export, rolling-window SLO monitor) and threads through every
seam here via `build_scheduler`'s ServeConfig telemetry knobs
(--metrics-out/--metrics-jsonl/--trace/--slo-ttft-ms/--slo-itl-ms);
SchedulerStats is a façade over the same registry the exporters read.
"""

from flexflow_tpu.serving.api import (
    ServeConfig,
    build_journal,
    build_proposer,
    build_restore_decider,
    build_scheduler,
    build_telemetry,
    generate,
)
from flexflow_tpu.telemetry import Telemetry
from flexflow_tpu.serving.engine import (
    GenerationEngine,
    InflightStep,
    snapshot,
)
from flexflow_tpu.serving.faults import (
    DraftFault,
    FaultError,
    FaultInjector,
    FaultPlan,
    KernelFault,
    ProcessCrash,
)
from flexflow_tpu.serving.journal import (
    JournalCorrupt,
    RecoveredRequest,
    RecoveryState,
    RequestJournal,
    read_journal,
    readmit,
    recover_journal,
)
from flexflow_tpu.serving.kv_cache import (
    KVCache,
    KVCacheSpec,
    PagedKVCache,
    PagePoolExhausted,
    default_buckets,
    default_page_size,
)
from flexflow_tpu.serving.scheduler import (
    TERMINAL_STATUSES,
    AsyncContinuousBatchingScheduler,
    ContinuousBatchingScheduler,
    Request,
    RequestStatus,
    SchedulerStats,
    StaticBatchingScheduler,
    latency_percentiles,
)
from flexflow_tpu.serving.spec import (
    DraftProposer,
    DraftTree,
    ModelDraftProposer,
    NGramDraftProposer,
    accept_drafts,
    accept_tree,
)
from flexflow_tpu.serving.tenancy import (
    AdapterPool,
    AdapterPoolExhausted,
    DeficitRoundRobin,
    PriorityClass,
    make_lora_weights,
    parse_classes,
)
from flexflow_tpu.serving.frontend import (
    DisaggregatedPipeline,
    EngineReplica,
    FrontDoor,
    PrefillOnlyScheduler,
    ReplicaRouter,
    StreamEvent,
    serve_tcp,
)

__all__ = [
    "ServeConfig",
    "generate",
    "build_proposer",
    "build_scheduler",
    "build_telemetry",
    "Telemetry",
    "GenerationEngine",
    "InflightStep",
    "snapshot",
    "KVCache",
    "KVCacheSpec",
    "PagedKVCache",
    "default_buckets",
    "default_page_size",
    "Request",
    "RequestStatus",
    "TERMINAL_STATUSES",
    "AsyncContinuousBatchingScheduler",
    "ContinuousBatchingScheduler",
    "StaticBatchingScheduler",
    "SchedulerStats",
    "latency_percentiles",
    "FaultError",
    "FaultInjector",
    "FaultPlan",
    "KernelFault",
    "DraftFault",
    "ProcessCrash",
    "RequestJournal",
    "JournalCorrupt",
    "RecoveredRequest",
    "RecoveryState",
    "read_journal",
    "readmit",
    "recover_journal",
    "build_journal",
    "build_restore_decider",
    "PagePoolExhausted",
    "DraftProposer",
    "DraftTree",
    "ModelDraftProposer",
    "NGramDraftProposer",
    "accept_drafts",
    "accept_tree",
    "AdapterPool",
    "AdapterPoolExhausted",
    "DeficitRoundRobin",
    "PriorityClass",
    "make_lora_weights",
    "parse_classes",
    "DisaggregatedPipeline",
    "EngineReplica",
    "FrontDoor",
    "PrefillOnlyScheduler",
    "ReplicaRouter",
    "StreamEvent",
    "serve_tcp",
]
