"""Runtime configuration + CLI flag parsing.

Re-design of FFConfig (reference: include/flexflow/config.h:92-165,
FFConfig::parse_args src/runtime/model.cc:3541-3697). The Legion `-ll:*`
resource flags become mesh/topology settings; search and training flags keep
the reference's spellings so the example scripts read the same.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

# reference: config.h:40-53 compile-time bounds
MAX_NUM_INPUTS = 256
MAX_NUM_WEIGHTS = 64
MAX_NUM_OUTPUTS = 256
MAX_NUM_WORKERS = 1024


@dataclasses.dataclass
class FFConfig:
    # training (reference flags -e/-b/--lr/--wd)
    epochs: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    iterations: Optional[int] = None
    # -p/--print-freq: metric print cadence in iterations (reference:
    # FFConfig.printFreq, model.cc:3563; 0 = per-epoch only). Printing
    # forces a device sync, so the loop only pays it on schedule.
    print_freq: int = 0
    # -d/--dataset: dataset directory (reference: dataset_path,
    # model.cc:3567); keras_datasets honors it like FF_DATASETS_DIR
    dataset_path: str = ""

    # sparse embedding-table updates (beyond-reference: the reference's
    # embedding bwd scatter-adds into a DENSE weight-grad region,
    # embedding_kernels.cu — here eligible tables skip the dense gradient
    # and per-step full-table optimizer pass entirely; --no-sparse-embedding
    # disables for A/B)
    sparse_embedding_update: bool = True

    # machine (reference: -ll:gpu/-ll:cpu + numNodes)
    num_nodes: int = 1
    workers_per_node: int = 0  # 0 = use all local devices
    chip: str = "v4"

    # search (reference: --budget/--alpha/--import/--export/…)
    search_budget: int = 0
    search_alpha: float = 1.05
    import_strategy_file: str = ""
    export_strategy_file: str = ""
    only_data_parallel: bool = False
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    enable_sample_parallel: bool = False
    enable_inplace_optimizations: bool = False
    base_optimize_threshold: int = 10  # reference: config.h:155
    substitution_json: str = ""
    # the bundled default rewrite set runs at every compile (the reference
    # runs base_optimize as a core graph_optimize phase, not opt-in);
    # --no-substitution turns it off
    enable_substitution: bool = True
    # search-without-hardware overrides (reference: model.cc:3673-3680)
    search_num_nodes: int = -1
    search_num_workers: int = -1
    # which engine a nonzero --budget runs: "mesh" (mesh × rewrite-site
    # search, search.auto), "unity" (per-op-view DP, search.unity — the
    # reference's Unity path, graph.cc:1346), or "mcmc" (simulated
    # annealing, search.mcmc — the reference's legacy path, model.cc:3271)
    search_engine: str = "mesh"
    # machine model for the search's comm costs (reference:
    # --machine-model-version/-file, model.cc:3650+; graph.cc:1566-1581):
    # 0 = simple ring formulas, 1 = Enhanced from file, 2 = Networked torus
    machine_model_version: int = 0
    machine_model_file: str = ""
    # measured-kernel search calibration (reference: the simulator ALWAYS
    # times real kernels, simulator.cc:532-572; here it is opt-in because
    # the analytic roofline keeps search-without-hardware working).
    # calibration_file persists the measured table across runs.
    measure_costs: bool = False
    calibration_file: str = ""
    # search observability (flexflow_tpu.telemetry.search_trace):
    # --search-trace exports every candidate the strategy search
    # considered as schema-validated JSONL (plus a Chrome trace-event
    # timeline of the search phases as <path>.trace.json);
    # --explain prints the explain_strategy() report — why the winning
    # strategy won — after the search (and alongside any exported
    # trace, which `python -m flexflow_tpu.search.explain` re-reads)
    search_trace_file: str = ""
    search_explain: bool = False

    # runtime
    perform_fusion: bool = False  # reference: --fusion
    profiling: bool = False
    seed: int = 0
    # numerics: bf16 matmul operands with f32 accumulation (reference:
    # --allow-tensor-op-math-conversion picks TF32/FP16 tensor cores,
    # model.cc:3668 — off by default there too)
    allow_mixed_precision: bool = False

    # visualization dumps (reference: --compgraph/--taskgraph/--export-strategy)
    computation_graph_file: str = ""
    task_graph_file: str = ""
    include_costs_dot_graph: bool = False

    # per-iteration dynamic config (reference: FFIterationConfig, config.h:160)
    seq_length: Optional[int] = None

    # serving (flexflow_tpu.serving; upstream grew the same flags in
    # FlexFlow Serve's RequestManager): KV-cache slots, cache length per
    # slot, scheduler kind, EOS token (-1 = none). ServeConfig.from_config
    # lifts these into the engine.
    serve_max_seqs: int = 8
    serve_max_seq_len: int = 256
    serve_scheduler: str = "continuous"
    serve_eos_token: int = -1
    # paged KV cache geometry (PagedAttention): layout "paged" | "slot",
    # page size in tokens (0 = auto) and pool pages (0 = derived from
    # max_seqs * max_seq_len so default capacity matches the slot layout)
    serve_kv_layout: str = "paged"
    serve_kv_page_size: int = 0
    serve_kv_pages: int = 0
    # --kv-dtype: K/V pool element type, "fp32" | "int8" (int8 stores
    # fp32 scales per page per head in side pools; paged layout only)
    serve_kv_dtype: str = "fp32"
    # --prefix-cache: hashed prefix-page cache with copy-on-write
    # forking — admissions map content-matching full pages instead of
    # recomputing them (paged layout only)
    serve_prefix_cache: bool = False
    # speculative decoding (SpecInfer; serving/spec.py): draft source
    # ("" = off, "ngram" = weight-free prompt lookup, "model" = second
    # decoder LM passed to build_scheduler) and draft length per verify
    serve_spec_draft: str = ""
    serve_spec_k: int = 4
    # --spec-branch: token-TREE speculation (SpecInfer tree verify) —
    # branching factor per draft level; 1 keeps the linear chain path,
    # > 1 verifies a deduped tree of up to spec_k * spec_branch nodes
    # in one call and accepts the longest surviving root-to-leaf path
    serve_spec_branch: int = 1
    # chunked prefill (Sarathi-style; serving/scheduler.py):
    # --token-budget > 0 caps each iteration's token work and streams
    # prompts in via --chunk-size-aligned chunks interleaved with
    # decodes; 0 keeps the monolithic admission prefill
    serve_token_budget: int = 0
    serve_chunk_size: int = 16
    # decode/verify attention core (ops/pallas/decode_kernel.py):
    # "auto" = Pallas flash-decode kernel on TPU when supported,
    # "pallas" = force it (interpret mode off-TPU), "dense" = jnp paths
    serve_decode_kernel: str = "auto"
    # paged admission policy (serving/scheduler.py): "reserve" =
    # preemption-free worst-case gate, "optimistic" = admit beyond the
    # reserve and preempt-by-recompute on pool exhaustion, up to
    # --max-preemptions per request
    serve_admission: str = "reserve"
    serve_max_preemptions: int = 3
    # --serve-async: the double-buffered engine loop — dispatch step
    # N+1 while N is in flight, reconcile terminal events one step late
    serve_async: bool = False
    # --check-invariants: run cache.check_invariants() every scheduler
    # iteration (the chaos harness's probe) — debugging/CI posture
    serve_check_invariants: bool = False
    # telemetry (flexflow_tpu.telemetry): --metrics-out writes
    # Prometheus text exposition at the end of a serve OR fit run,
    # --metrics-jsonl streams one sample row per scheduler/training
    # iteration, --trace writes a Chrome trace-event JSON
    # (Perfetto-loadable), --slo-ttft-ms / --slo-itl-ms set
    # rolling-window SLO thresholds (milliseconds; 0 = observe but
    # never count violations), and --serve-telemetry force-enables the
    # in-memory bundle without any output path. The same knobs drive
    # FFModel.fit's training telemetry (train_* series) — the fields
    # keep their historical serve_ prefix
    serve_metrics_out: str = ""
    serve_metrics_jsonl: str = ""
    serve_trace: str = ""
    serve_slo_ttft_ms: float = 0.0
    serve_slo_itl_ms: float = 0.0
    serve_telemetry: bool = False
    # pod-scale serving (serving/distributed.py): --serve-mesh "dp,tp"
    # applies that (data, model) serving mesh at compile_for_serving
    # ("" = search one when compile_for_serving runs; serving without
    # compile_for_serving keeps inheriting the training sharding),
    # --serve-hosts partitions slots/pages across N host views (0 =
    # process count on pods, else the data-axis degree; >1 on the slot
    # KV layout is rejected), --serve-export-strategy writes the
    # applied placement doc (fxlint strategy-validate input)
    serve_mesh: str = ""
    serve_hosts: int = 0
    serve_export_strategy: str = ""
    # graceful degradation under pressure (serving/kv_cache.py +
    # scheduler.py): --kv-swap stages preemption victims' pages to host
    # buffers and restores them at re-admission (no re-prefill),
    # --kv-swap-bytes caps the host bytes held at once (0 = unbounded),
    # --prefix-evict "lru" lets publication-only prefix pages be
    # reclaimed under pool pressure before any live request is
    # preempted ("none" retains them forever)
    serve_kv_swap: bool = False
    serve_kv_swap_bytes: int = 0
    serve_prefix_evict: str = "none"
    # device-resident multi-step decode (serving/engine.py +
    # scheduler.py): --decode-multistep fuses scheduler-invariant runs
    # of decode iterations into one jitted lax.scan window of up to
    # --max-fused-steps steps, reconciled in a single host sync
    serve_decode_multistep: bool = False
    serve_max_fused_steps: int = 8
    # multi-tenant serving (serving/tenancy/): --adapters provisions a
    # paged pool of that many LoRA adapter ids (--adapter-rank rows
    # each); --classes "gold:4:200:20,bronze:1" declares priority
    # classes as name:weight[:ttft_ms[:itl_ms]] and turns the token
    # planner/admission into weighted-fair deficit round-robin
    serve_adapters: int = 0
    serve_adapter_rank: int = 8
    serve_classes: str = ""
    # durable serving (serving/journal.py): --journal attaches an
    # append-only write-ahead request journal at that path (submit/
    # commit/terminal records at the host-sync grain — a crash-restart
    # rebuilds token-identical streams from it); --journal-fsync picks
    # the durability point (commit|batch|off); --journal-snapshot-every
    # N journals a KV snapshot of every running slot each N iterations
    # (paged layout), priced at recovery against recompute.
    # --door-max-pending bounds the front door's admission backlog
    # (past it, per-class weighted-share shedding refuses with a
    # retry-after hint); --breaker-threshold / --breaker-cooldown
    # configure the per-replica circuit breaker.
    serve_journal: str = ""
    serve_journal_fsync: str = "batch"
    serve_journal_snapshot_every: int = 0
    serve_door_max_pending: int = 0
    serve_breaker_threshold: int = 0
    serve_breaker_cooldown: int = 8

    @property
    def num_devices(self) -> int:
        import jax

        if self.workers_per_node <= 0:
            return len(jax.devices()) * max(1, self.num_nodes) // max(1, self.num_nodes)
        return self.num_nodes * self.workers_per_node

    def total_workers(self) -> int:
        if self.workers_per_node > 0:
            return self.num_nodes * self.workers_per_node
        import jax

        return len(jax.devices())

    def get_current_time(self) -> float:
        """reference: FFConfig.get_current_time (flexflow_cffi.py) —
        microseconds; scripts compute 1e-6*(end-start) for seconds."""
        import time

        return time.perf_counter() * 1e6

    @staticmethod
    def parse_args(argv: Optional[Sequence[str]] = None) -> "FFConfig":
        """Parse the reference's CLI spellings (model.cc:3541-3697)."""
        import sys

        cfg = FFConfig()
        args = list(sys.argv[1:] if argv is None else argv)
        i = 0

        def take():
            nonlocal i
            i += 1
            return args[i]

        while i < len(args):
            a = args[i]
            if a in ("-e", "--epochs"):
                cfg.epochs = int(take())
            elif a in ("-b", "--batch-size"):
                cfg.batch_size = int(take())
            elif a == "--lr" or a == "--learning-rate":
                cfg.learning_rate = float(take())
            elif a == "--wd" or a == "--weight-decay":
                cfg.weight_decay = float(take())
            elif a in ("-i", "--iterations"):
                cfg.iterations = int(take())
            elif a in ("-p", "--print-freq"):
                cfg.print_freq = int(take())
            elif a in ("-d", "--dataset"):
                cfg.dataset_path = take()
            elif a == "--budget" or a == "--search-budget":
                cfg.search_budget = int(take())
            elif a == "--alpha" or a == "--search-alpha":
                cfg.search_alpha = float(take())
            elif a == "--import" or a == "--import-strategy":
                cfg.import_strategy_file = take()
            elif a == "--export" or a == "--export-strategy":
                cfg.export_strategy_file = take()
            elif a == "--only-data-parallel":
                cfg.only_data_parallel = True
            elif a == "--enable-parameter-parallel":
                cfg.enable_parameter_parallel = True
            elif a == "--enable-attribute-parallel":
                cfg.enable_attribute_parallel = True
            elif a == "--enable-sample-parallel":
                cfg.enable_sample_parallel = True
            elif a == "--base-optimize-threshold":
                cfg.base_optimize_threshold = int(take())
            elif a == "--substitution-json":
                cfg.substitution_json = take()
            elif a == "--no-substitution":
                cfg.enable_substitution = False
            elif a == "--no-sparse-embedding":
                cfg.sparse_embedding_update = False
            elif a == "--search-num-nodes":
                cfg.search_num_nodes = int(take())
            elif a == "--search-num-workers":
                cfg.search_num_workers = int(take())
            elif a == "--search-engine":
                cfg.search_engine = take()
            elif a == "--machine-model-version":
                cfg.machine_model_version = int(take())
            elif a == "--machine-model-file":
                cfg.machine_model_file = take()
            elif a == "--measure-costs":
                cfg.measure_costs = True
            elif a == "--calibration-file":
                cfg.calibration_file = take()
            elif a == "--search-trace":
                cfg.search_trace_file = take()
            elif a == "--explain":
                cfg.search_explain = True
            elif a == "--fusion":
                cfg.perform_fusion = True
            elif a == "--allow-tensor-op-math-conversion":
                cfg.allow_mixed_precision = True
            elif a == "--profiling":
                cfg.profiling = True
            elif a == "--seed":
                cfg.seed = int(take())
            elif a == "--compgraph":
                cfg.computation_graph_file = take()
            elif a == "--include-costs-dot-graph":
                cfg.include_costs_dot_graph = True
            elif a == "--taskgraph":
                cfg.task_graph_file = take()
            elif a == "--nodes":
                cfg.num_nodes = int(take())
            elif a == "-ll:gpu" or a == "-ll:tpu" or a == "--workers-per-node":
                cfg.workers_per_node = int(take())
            elif a == "--chip":
                cfg.chip = take()
            elif a == "--max-seqs":
                cfg.serve_max_seqs = int(take())
            elif a == "--max-seq-len":
                cfg.serve_max_seq_len = int(take())
            elif a == "--serve-scheduler":
                cfg.serve_scheduler = take()
            elif a == "--kv-layout":
                cfg.serve_kv_layout = take()
            elif a == "--kv-page-size":
                cfg.serve_kv_page_size = int(take())
            elif a == "--kv-pages":
                cfg.serve_kv_pages = int(take())
            elif a == "--kv-dtype":
                cfg.serve_kv_dtype = take()
            elif a == "--prefix-cache":
                cfg.serve_prefix_cache = True
            elif a == "--eos-token":
                cfg.serve_eos_token = int(take())
            elif a == "--spec-draft":
                cfg.serve_spec_draft = take()
            elif a == "--spec-k":
                cfg.serve_spec_k = int(take())
            elif a == "--spec-branch":
                cfg.serve_spec_branch = int(take())
            elif a == "--token-budget":
                cfg.serve_token_budget = int(take())
            elif a == "--chunk-size":
                cfg.serve_chunk_size = int(take())
            elif a == "--decode-kernel":
                cfg.serve_decode_kernel = take()
            elif a == "--admission":
                cfg.serve_admission = take()
            elif a == "--max-preemptions":
                cfg.serve_max_preemptions = int(take())
            elif a == "--serve-async":
                cfg.serve_async = True
            elif a == "--check-invariants":
                cfg.serve_check_invariants = True
            elif a == "--metrics-out":
                cfg.serve_metrics_out = take()
            elif a == "--metrics-jsonl":
                cfg.serve_metrics_jsonl = take()
            elif a == "--trace":
                cfg.serve_trace = take()
            elif a == "--slo-ttft-ms":
                cfg.serve_slo_ttft_ms = float(take())
            elif a == "--slo-itl-ms":
                cfg.serve_slo_itl_ms = float(take())
            elif a == "--serve-telemetry":
                cfg.serve_telemetry = True
            elif a == "--serve-mesh":
                cfg.serve_mesh = take()
            elif a == "--serve-hosts":
                cfg.serve_hosts = int(take())
            elif a == "--serve-export-strategy":
                cfg.serve_export_strategy = take()
            elif a == "--kv-swap":
                cfg.serve_kv_swap = True
            elif a == "--kv-swap-bytes":
                cfg.serve_kv_swap_bytes = int(take())
            elif a == "--prefix-evict":
                cfg.serve_prefix_evict = take()
            elif a == "--decode-multistep":
                cfg.serve_decode_multistep = True
            elif a == "--max-fused-steps":
                cfg.serve_max_fused_steps = int(take())
            elif a == "--adapters":
                cfg.serve_adapters = int(take())
            elif a == "--adapter-rank":
                cfg.serve_adapter_rank = int(take())
            elif a == "--classes":
                cfg.serve_classes = take()
            elif a == "--journal":
                cfg.serve_journal = take()
            elif a == "--journal-fsync":
                cfg.serve_journal_fsync = take()
            elif a == "--journal-snapshot-every":
                cfg.serve_journal_snapshot_every = int(take())
            elif a == "--door-max-pending":
                cfg.serve_door_max_pending = int(take())
            elif a == "--breaker-threshold":
                cfg.serve_breaker_threshold = int(take())
            elif a == "--breaker-cooldown":
                cfg.serve_breaker_cooldown = int(take())
            # silently accept remaining legion-style flags with one value
            elif a.startswith("-ll:") or a.startswith("-lg:"):
                take()
            i += 1
        return cfg
