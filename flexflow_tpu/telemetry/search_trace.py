"""Search tracing: every candidate the strategy search considered.

FlexFlow's defining loop is *measure, then decide* — the cost simulator
(calibrated against profiled kernels) drives the substitution +
MachineView search. Until now the decision half was a black box:
``UnitySearch.optimize()`` and ``mcmc_optimize`` returned one winner
and discarded every candidate they rejected on the way, so nothing
could answer "why THIS strategy?" the way TASO-style systems justify
rewrites by exposing per-candidate cost deltas.

``SearchTrace`` is the recorder the search engines
(`search/unity.py`, `search/mcmc.py`, `search/auto.py`) and the
simulator (`search/simulator.py`) emit into:

* a **header** — engine, seed, budget, temperature schedule, machine
  description, graph summary — enough to reproduce the run from the
  artifact alone;
* **candidate** records, one per considered option with a monotone
  ``id``: per-(op, ViewOption) leaf costs tagged ``measured`` /
  ``analytic`` / ``sparse``, MCMC proposals with their cost delta and
  accept/reject verdict, whole-config ``GraphCost`` breakdowns
  (compute / comm / sync / update / memory feasibility);
* **phase** records mirrored as Chrome trace-event spans (reusing
  `telemetry.trace.Tracer`) so the search timeline — view enumeration,
  native vs python DP, MCMC sweep, lowering — renders in Perfetto;
* one **result** record carrying the winning total plus a per-op
  ``(op_cost, xfer_cost)`` breakdown and an explicit ``residual`` term
  (DP concurrency credit, dispatch floor, incremental-delta drift)
  such that summing the breakdown in record order and adding the
  residual reproduces the winner's total cost exactly —
  `search.explain.explain_strategy` relies on this identity.

Export is JSONL (one record per line) validated by
``schemas/search_trace.schema.json`` via
`telemetry.validate.validate_search_trace`; ``save()`` also writes the
phase timeline as ``<path>.trace.json`` when any phase was recorded.

Discipline: record values are SCALARS and freshly-built containers —
never references to live search state (the searcher keeps mutating its
view maps after the record is taken; a captured reference would let
rows rewrite themselves retroactively). fxlint FX104 enforces this the
same way FX101 guards jit dispatch.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

__all__ = ["SearchTrace"]

#: process lane for the search timeline in the Chrome trace export
PID_SEARCH = 3
TID_SEARCH = 1


class SearchTrace:
    """Append-only recorder for one strategy-search run."""

    def __init__(
        self,
        engine: str = "",
        path: str = "",
        registry=None,
        timeline: bool = True,
        max_records: int = 2_000_000,
    ):
        """`registry`: an optional telemetry.MetricsRegistry to mirror
        the serve-style ``search_*`` counters/gauges into. `timeline`:
        record phase spans into an owned Tracer (exported as a sibling
        ``.trace.json``)."""
        self.engine = engine
        self.path = path
        self.t0 = time.perf_counter()
        self.records: List[dict] = []
        self.dropped_records = 0
        self.max_records = int(max_records)
        self._header: Optional[dict] = None
        self._result: Optional[dict] = None
        self._next_id = 0
        # accept/reject + cost-source tallies (mirrored into the result
        # record and, when a registry is attached, into search_* metrics)
        self.candidates = 0
        self.accepted = 0
        self.rejected = 0
        self.measured_hits = 0
        self.analytic_estimates = 0
        self.registry = registry
        self._metrics = None
        if registry is not None:
            self._metrics = {
                "candidates": registry.counter(
                    "search_candidates_total",
                    help="candidates considered by the strategy search",
                ),
                "accepted": registry.counter(
                    "search_accepted_total",
                    help="candidates accepted (improvements + annealing)",
                ),
                "rejected": registry.counter(
                    "search_rejected_total",
                    help="candidates rejected by the strategy search",
                ),
                "measured": registry.counter(
                    "search_measured_lut_hits_total",
                    help="leaf costs served by calibrated kernel "
                    "measurements",
                ),
                "analytic": registry.counter(
                    "search_analytic_estimates_total",
                    help="leaf costs served by the analytic roofline",
                ),
                "best_cost": registry.gauge(
                    "search_best_cost_ms",
                    help="best simulated step time found so far (ms)",
                ),
                "seed": registry.gauge(
                    "search_seed", help="RNG seed of the search run"
                ),
                "resets": registry.counter(
                    "search_resets_total",
                    help="MCMC resets to the best-so-far configuration",
                ),
            }
        self.tracer = None
        if timeline:
            from flexflow_tpu.telemetry.trace import Tracer

            self.tracer = Tracer()
            self.tracer._meta(
                PID_SEARCH, None, "process_name", "flexflow_tpu.search"
            )
            self.tracer._meta(
                PID_SEARCH, TID_SEARCH, "thread_name", "strategy search"
            )
            # share the clock origin so search spans and any sibling
            # telemetry line up
            self.tracer.t0 = self.t0

    # -- low level -------------------------------------------------------------

    def now(self) -> float:
        return time.perf_counter()

    def _push(self, rec: dict) -> None:
        if len(self.records) >= self.max_records:
            self.dropped_records += 1
            return
        self.records.append(rec)

    # -- recording -------------------------------------------------------------

    def header(self, **fields) -> None:
        """Set/merge the run header (engine, seed, budget, temperature
        schedule, machine, graph summary). Mergeable so the entry point
        and the engine can each contribute their fields; always emitted
        as the FIRST record."""
        if self._header is None:
            self._header = {"type": "header", "version": 1}
        self._header.update(fields)
        if "seed" in fields and self._metrics is not None:
            seed = fields["seed"]
            if seed is not None:
                self._metrics["seed"].set(float(seed))

    @contextmanager
    def phase(self, name: str, **fields):
        """One search phase: a record with [t_start_s, t_end_s] plus a
        span on the search lane of the Chrome timeline."""
        t_start = self.now()
        try:
            yield
        finally:
            t_end = self.now()
            rec = {
                "type": "phase",
                "name": name,
                "t_start_s": round(t_start - self.t0, 9),
                "t_end_s": round(t_end - self.t0, 9),
            }
            rec.update(fields)
            self._push(rec)
            if self.tracer is not None:
                self.tracer.complete(
                    name, "search", t_start, t_end,
                    pid=PID_SEARCH, tid=TID_SEARCH,
                )

    def candidate(
        self,
        kind: str,
        accepted: Optional[bool] = None,
        source: Optional[str] = None,
        best_cost: Optional[float] = None,
        **fields,
    ) -> int:
        """One considered option. Returns its monotone id. `kind` names
        the candidate class ("op_view" leaf, "flip"/"propagate" MCMC
        proposal, "graph_cost" whole-config estimate, "extra_axis"
        family candidate). `source` tags where a leaf cost came from
        ("measured" | "analytic" | "sparse"). Pass SCALARS or freshly
        built containers only — never live search state (FX104)."""
        cid = self._next_id
        self._next_id += 1
        self.candidates += 1
        rec = {"type": "candidate", "id": cid, "kind": kind}
        if accepted is not None:
            rec["accepted"] = bool(accepted)
            if accepted:
                self.accepted += 1
            else:
                self.rejected += 1
        if source is not None:
            rec["source"] = source
            if source == "measured":
                self.measured_hits += 1
            elif source == "analytic":
                self.analytic_estimates += 1
        if best_cost is not None:
            rec["best_cost"] = best_cost
        rec.update(fields)
        self._push(rec)
        m = self._metrics
        if m is not None:
            m["candidates"].inc()
            if accepted is not None:
                (m["accepted"] if accepted else m["rejected"]).inc()
            if source == "measured":
                m["measured"].inc()
            elif source == "analytic":
                m["analytic"].inc()
            if best_cost is not None:
                m["best_cost"].set(best_cost * 1e3)
        return cid

    def event(self, name: str, **fields) -> None:
        """A point event (e.g. an MCMC reset-to-best)."""
        rec = {"type": "event", "name": name}
        rec.update(fields)
        self._push(rec)
        if name == "reset" and self._metrics is not None:
            self._metrics["resets"].inc()
        if self.tracer is not None:
            self.tracer.instant(
                name, "search", pid=PID_SEARCH, tid=TID_SEARCH,
                args={k: v for k, v in fields.items()
                      if isinstance(v, (int, float, str, bool))},
            )

    def result(
        self,
        total_cost: float,
        ops: Optional[List[dict]] = None,
        residual: float = 0.0,
        **fields,
    ) -> None:
        """The winning strategy. `ops` is the per-op breakdown (each
        entry {guid, name, op, dp, ch, op_cost, xfer_cost}); summing
        op_cost + xfer_cost over the entries IN ORDER and adding
        `residual` must reproduce `total_cost` (the explain-report
        identity — callers compute residual as the difference, which
        floating-point addition then inverts to within 1 ulp). Emitted
        LAST; calling again replaces the record (a later stage — the
        extra-axis gate — may override the engine's pick)."""
        rec = {
            "type": "result",
            "engine": self.engine,
            "total_cost": total_cost,
            "residual": residual,
            "candidates": self.candidates,
            "accepted_count": self.accepted,
            "rejected_count": self.rejected,
            "measured_hits": self.measured_hits,
            "analytic_estimates": self.analytic_estimates,
            "duration_s": round(self.now() - self.t0, 9),
        }
        if ops is not None:
            rec["ops"] = list(ops)
        rec.update(fields)
        self._result = rec
        if self._metrics is not None:
            self._metrics["best_cost"].set(total_cost * 1e3)

    # -- export ----------------------------------------------------------------

    def rows(self) -> List[dict]:
        """Header first, candidates/phases/events in record order, the
        result last — the JSONL line order and the order explain
        consumes."""
        header = dict(self._header) if self._header is not None else {
            "type": "header", "version": 1
        }
        header.setdefault("engine", self.engine)
        if self.dropped_records:
            header["dropped_records"] = self.dropped_records
        out = [header]
        out.extend(self.records)
        if self._result is not None:
            out.append(self._result)
        return out

    def to_jsonl(self) -> str:
        return "\n".join(
            json.dumps(r, sort_keys=True) for r in self.rows()
        ) + "\n"

    def timeline_path(self, path: Optional[str] = None) -> str:
        """Sibling path for the Chrome timeline export."""
        path = path or self.path
        base = path[: -len(".jsonl")] if path.endswith(".jsonl") else path
        return base + ".trace.json"

    def save(self, path: Optional[str] = None) -> str:
        """Write the JSONL artifact (and the phase timeline as
        `<path>.trace.json` when phases were recorded). Returns the
        JSONL path."""
        path = path or self.path
        if not path:
            raise ValueError("SearchTrace.save: no path configured")
        with open(path, "w") as f:
            f.write(self.to_jsonl())
        if self.tracer is not None and any(
            e.get("ph") in ("X", "i") for e in self.tracer.events
        ):
            self.tracer.save(self.timeline_path(path))
        return path
