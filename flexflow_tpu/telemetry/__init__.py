"""flexflow_tpu.telemetry: tracing, metrics, and SLO monitoring.

FlexFlow's core loop is *measure, then decide* — the simulator profiles
real kernels before the search commits to a strategy. This package is
that posture applied to the serving runtime, in three pillars:

* **metrics registry** (`registry`) — counters / gauges / fixed-bucket
  histograms with Prometheus text exposition (`--metrics-out`) and a
  per-iteration JSONL time series (`--metrics-jsonl`). SchedulerStats
  is a façade over this registry, so the exported text IS the stats
  surface the benches and tests already read.
* **trace layer** (`trace`) — Chrome trace-event spans for the request
  lifecycle (QUEUED→RUNNING→terminal, rebuilt from the `events` audit
  log) and the engine phases (prefill, dispatch, reconcile, in-flight
  device windows, preemption, kernel fallback), exported via `--trace`
  and loadable in Perfetto — the async pipeline's one-step-stale
  overlap as a picture, not a scalar.
* **SLO monitor** (`slo`) — rolling-window p50/p95/p99 TTFT,
  inter-token latency, and goodput, with `--slo-ttft-ms` /
  `--slo-itl-ms` thresholds feeding `serve_slo_violations_total` — the
  hook the token-budget scheduler (ROADMAP chunked-prefill item) will
  price against.

The `Telemetry` facade bundles the three and owns the output paths;
`serving.build_scheduler` threads one instance through the engine,
scheduler, cache, and fault injector. Cost discipline: when no
Telemetry is attached the serving hot path takes a single predicate
branch per hook and allocates nothing — proved by the bench gate
(bench_serve.py --telemetry: disabled-telemetry throughput within 2%
of the uninstrumented baseline).
"""

from __future__ import annotations

import time
from typing import Optional

from flexflow_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS_MS,
    DURABILITY_METRICS,
    Counter,
    Gauge,
    Histogram,
    JsonlWriter,
    MetricsRegistry,
    register_durability_metrics,
    series_name,
)
from flexflow_tpu.telemetry.search_trace import SearchTrace
from flexflow_tpu.telemetry.slo import RollingWindow, SLOMonitor, percentiles
from flexflow_tpu.telemetry.trace import (
    PID_ENGINE,
    PID_REQUESTS,
    TID_DEVICE0,
    TID_HOST,
    Tracer,
)
from flexflow_tpu.telemetry.validate import (
    ValidationError,
    check_schema,
    load_schema,
    validate_durability_metrics,
    validate_metrics_jsonl,
    validate_metrics_jsonl_file,
    validate_metrics_text,
    validate_search_trace,
    validate_search_trace_file,
    validate_trace,
    validate_trace_file,
)

__all__ = [
    "Telemetry",
    "build_telemetry",
    "NullTracer",
    "SearchTrace",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlWriter",
    "series_name",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "DURABILITY_METRICS",
    "register_durability_metrics",
    "validate_durability_metrics",
    "Tracer",
    "SLOMonitor",
    "RollingWindow",
    "percentiles",
    "ValidationError",
    "check_schema",
    "load_schema",
    "validate_trace",
    "validate_trace_file",
    "validate_metrics_jsonl",
    "validate_metrics_jsonl_file",
    "validate_metrics_text",
    "validate_search_trace",
    "validate_search_trace_file",
    "PID_ENGINE",
    "PID_REQUESTS",
    "TID_HOST",
    "TID_DEVICE0",
]


class NullTracer:
    """No-op Tracer twin: attached when metrics are wanted but tracing
    is not, so instrument points never branch on 'is tracing on'. Every
    recording method swallows its arguments; export methods are
    errors (there is nothing to export)."""

    events = ()
    enabled = False

    def now(self) -> float:
        return 0.0

    def complete(self, *a, **k) -> None:
        pass

    def instant(self, *a, **k) -> None:
        pass

    def device_window(self, *a, **k) -> None:
        pass

    def request_lifecycle(self, req) -> None:
        pass

    def host_lane(self, host: int) -> int:
        return 0

    def replica_lane(self, replica: int) -> int:
        return 0

    def span(self, *a, **k):
        return _NULL_CM

    def save(self, path: str) -> None:
        raise RuntimeError("tracing is disabled — no trace to save")


class _NullCM:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullCM()


class Telemetry:
    """The bundle one serving session records into.

    Construction chooses the pillars: metrics always (the registry is
    the cheap part), tracing when `trace` names a path or
    `trace_enabled` forces it in-memory, SLO thresholds when the
    `slo_*_ms` knobs are nonzero (rolling windows fill either way so
    the percentile gauges always mean something). `flush()` writes
    whatever paths were configured and is idempotent — schedulers call
    it at the end of `run()`, external drivers call it themselves.
    """

    enabled = True

    def __init__(
        self,
        metrics_out: str = "",
        metrics_jsonl: str = "",
        trace: str = "",
        trace_enabled: Optional[bool] = None,
        slo_ttft_ms: float = 0.0,
        slo_itl_ms: float = 0.0,
        slo_window: int = 1024,
    ):
        self.t0 = time.perf_counter()
        self.metrics_out = metrics_out
        self.trace_path = trace
        self.registry = MetricsRegistry()
        if trace_enabled is None:
            trace_enabled = bool(trace)
        self.tracer = Tracer() if trace_enabled else NullTracer()
        self.slo = SLOMonitor(
            self.registry,
            ttft_ms=slo_ttft_ms,
            itl_ms=slo_itl_ms,
            window=slo_window,
        )
        self._jsonl = JsonlWriter(metrics_jsonl) if metrics_jsonl else None
        self._flushed = False
        # the per-iteration time series only has a consumer when a
        # JSONL path is configured: without one, `sample()` skips the
        # row build AND the rolling-percentile refresh (np.percentile
        # over the windows) — exposition refreshes them at flush/render
        # instead. This is what keeps the in-memory bundle inside the
        # 2% overhead gate (bench_serve.py --telemetry).
        self.wants_samples = self._jsonl is not None

    @property
    def tracing(self) -> bool:
        return isinstance(self.tracer, Tracer)

    # -- per-iteration sampling ----------------------------------------------

    def sample(self, iteration: int) -> Optional[dict]:
        """Refresh the rolling-view gauges and take one JSONL row
        (streamed to `--metrics-jsonl`). The scheduler calls this at
        every iteration end; with no JSONL consumer it is a cheap
        no-op (see `wants_samples`)."""
        if not self.wants_samples:
            return None
        now = time.perf_counter()
        self.slo.publish(now)
        row = self.registry.sample(
            iteration=int(iteration), t_s=round(now - self.t0, 9)
        )
        self._jsonl.write(row)
        return row

    # -- export --------------------------------------------------------------

    def render_prometheus(self) -> str:
        self.slo.publish()
        return self.registry.render_prometheus()

    def flush(self) -> None:
        """Write every configured output path. Idempotent — later
        flushes overwrite with fresher data, which is what a metrics
        file wants."""
        self.slo.publish()
        if self.metrics_out:
            self.registry.write_prometheus(self.metrics_out)
        if self.trace_path and self.tracing:
            self.tracer.save(self.trace_path)
        if self._jsonl is not None:
            self._jsonl.close()
        self._flushed = True


def _cfg_field(cfg, name, default):
    """Read a telemetry knob off either surface: ServeConfig spells
    them bare (`metrics_out`), FFConfig with the serve_ prefix the CLI
    flags historically filled (`serve_metrics_out` — the SAME
    --metrics-out/--metrics-jsonl/--trace flags now drive training and
    search too)."""
    if hasattr(cfg, name):
        return getattr(cfg, name)
    return getattr(cfg, "serve_" + name, default)


def build_telemetry(config=None, **kwargs) -> Optional[Telemetry]:
    """The Telemetry bundle a config asks for, or None when every knob
    is off (callers then skip every instrument point on one predicate —
    the ≤2%-overhead contract both bench gates hold).

    `config` may be a serving.ServeConfig, an FFConfig, or omitted
    entirely; explicit kwargs (`metrics_out=`, `metrics_jsonl=`,
    `trace=`, `trace_enabled=`, `slo_ttft_ms=`, `slo_itl_ms=`,
    `slo_window=`, `telemetry=True` to force the in-memory bundle)
    override the config's fields. Training and search callers no
    longer fake a serving config to get a registry."""
    fields = {
        "metrics_out": "",
        "metrics_jsonl": "",
        "trace": "",
        "slo_ttft_ms": 0.0,
        "slo_itl_ms": 0.0,
        "slo_window": 1024,
        "telemetry": False,
    }
    if config is not None:
        for name, default in list(fields.items()):
            fields[name] = _cfg_field(config, name, default)
    trace_enabled = kwargs.pop("trace_enabled", None)
    unknown = set(kwargs) - set(fields)
    if unknown:
        raise TypeError(
            f"build_telemetry: unknown knob(s) {sorted(unknown)}"
        )
    fields.update(kwargs)
    force = bool(fields.pop("telemetry"))
    requested = force or any(
        bool(fields[k])
        for k in ("metrics_out", "metrics_jsonl", "trace",
                  "slo_ttft_ms", "slo_itl_ms")
    )
    if not requested:
        return None
    if trace_enabled is None:
        trace_enabled = bool(fields["trace"]) or force or None
    return Telemetry(trace_enabled=trace_enabled, **fields)
