"""SLO monitor: rolling-window latency percentiles + threshold counters.

A serving SLO is a *promise about the recent past* — "p95 TTFT under
200 ms" means over the last N requests, not over the process lifetime
(a quiet hour would launder a bad minute) and not over one request (a
single outlier is not a violation regime). So the monitor keeps fixed-
size rolling windows of TTFT, inter-token latency, and goodput samples,
recomputes percentiles on demand from the live window, and counts
threshold crossings (`--slo-ttft-ms` / `--slo-itl-ms`) into the
registry's `serve_slo_violations_total{slo=...}` counter — the signal
the ROADMAP's token-budget scheduler will price chunk/decode mixes
against.

`percentiles()` here is THE percentile implementation for the serving
stack: `scheduler.latency_percentiles` (the post-hoc per-request view)
routes through it, so the rolling-window p95 and the post-hoc p95 agree
exactly whenever the window still holds every sample — the acceptance
check bench_serve's telemetry gate runs.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

import numpy as np

from flexflow_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS_MS,
    MetricsRegistry,
)

__all__ = ["percentiles", "RollingWindow", "SLOMonitor"]


def percentiles(
    values: Iterable[float], pcts: Sequence[float]
) -> Dict[float, float]:
    """{pct: value} over `values` (linear interpolation, numpy's
    default). All-zero result for an empty input — the post-hoc and
    rolling paths share this exact function, so they can never
    disagree on math."""
    vals = np.asarray(list(values), dtype=np.float64)
    if vals.size == 0:
        return {p: 0.0 for p in pcts}
    return {p: float(np.percentile(vals, p)) for p in pcts}


class RollingWindow:
    """Last `size` observations in a preallocated ring — `observe` is
    an index write (no allocation, hot-path safe), `values()`
    materializes the window in arrival order for exact percentiles."""

    def __init__(self, size: int = 1024):
        if size < 1:
            raise ValueError(f"window size must be >= 1, got {size}")
        self.size = int(size)
        self._buf = np.zeros(self.size, dtype=np.float64)
        self._n = 0  # total observations ever
        self._i = 0  # next write index

    def __len__(self) -> int:
        return min(self._n, self.size)

    @property
    def total(self) -> int:
        return self._n

    def observe(self, value: float) -> None:
        self._buf[self._i] = value
        self._i = (self._i + 1) % self.size
        self._n += 1

    def values(self) -> np.ndarray:
        """Window contents, oldest first."""
        if self._n < self.size:
            return self._buf[: self._n].copy()
        return np.concatenate([self._buf[self._i :], self._buf[: self._i]])

    def percentiles(self, pcts: Sequence[float]) -> Dict[float, float]:
        return percentiles(self.values(), pcts)


_PCTS = (50, 95, 99)


class SLOMonitor:
    """Rolling TTFT / inter-token-latency / goodput tracking with
    optional violation thresholds. Thresholds are milliseconds; 0
    disables the check (observation still happens, so the percentile
    gauges and histograms fill either way).

    Registry series: histograms `serve_ttft_ms` / `serve_itl_ms`
    (lifetime aggregates), counter
    `serve_slo_violations_total{slo="ttft"|"itl"}`, and gauges
    `serve_slo_{ttft,itl}_p{50,95,99}_ms` + `serve_goodput_tokens_per_s`
    refreshed by `publish()` (the per-iteration sampler calls it, so
    the JSONL time series carries the rolling view).

    `labels` scopes every series the monitor owns — the per-class SLO
    monitors (serving.tenancy.slo) are instances of THIS class with
    `labels={"class": name}`, so the unlabelled series stay the
    fleet-wide aggregate and per-class views ride the same JSONL rows
    as `name{class="gold"}` columns."""

    def __init__(
        self,
        registry: MetricsRegistry,
        ttft_ms: float = 0.0,
        itl_ms: float = 0.0,
        window: int = 1024,
        labels: Optional[Dict[str, str]] = None,
    ):
        if ttft_ms < 0 or itl_ms < 0:
            raise ValueError("SLO thresholds must be >= 0 (0 = disabled)")
        self.registry = registry
        self.labels = dict(labels) if labels else None
        self.ttft_ms = float(ttft_ms)
        self.itl_ms = float(itl_ms)
        self.ttft_window = RollingWindow(window)
        self.itl_window = RollingWindow(window)
        # goodput window: (finish perf_counter time, tokens) of FINISHED
        # requests — rate over the span the window covers
        self._goodput_t = RollingWindow(window)
        self._goodput_tokens = RollingWindow(window)
        self._hist_ttft = registry.histogram(
            "serve_ttft_ms",
            DEFAULT_LATENCY_BUCKETS_MS,
            help="submit-to-first-token latency (finished requests)",
            labels=self.labels,
        )
        self._hist_itl = registry.histogram(
            "serve_itl_ms",
            DEFAULT_LATENCY_BUCKETS_MS,
            help="inter-token latency (gap between consecutive emits)",
            labels=self.labels,
        )
        self._violations = {
            "ttft": registry.counter(
                "serve_slo_violations_total",
                help="observations past the configured SLO threshold",
                labels={**(self.labels or {}), "slo": "ttft"},
            ),
            "itl": registry.counter(
                "serve_slo_violations_total",
                labels={**(self.labels or {}), "slo": "itl"},
            ),
        }
        self._gauges = {
            (kind, p): registry.gauge(
                f"serve_slo_{kind}_p{p}_ms", labels=self.labels
            )
            for kind in ("ttft", "itl")
            for p in _PCTS
        }
        self._goodput_gauge = registry.gauge(
            "serve_goodput_tokens_per_s",
            help="rolling goodput: finished-request tokens per second",
            labels=self.labels,
        )

    # -- observation (hot path: O(1), no allocation) -------------------------

    def observe_ttft(self, seconds: float) -> None:
        ms = seconds * 1e3
        self.ttft_window.observe(ms)
        self._hist_ttft.observe(ms)
        if self.ttft_ms and ms > self.ttft_ms:
            self._violations["ttft"].inc()

    def observe_itl(self, seconds: float) -> None:
        ms = seconds * 1e3
        self.itl_window.observe(ms)
        self._hist_itl.observe(ms)
        if self.itl_ms and ms > self.itl_ms:
            self._violations["itl"].inc()

    def observe_finished(self, finish_t: float, tokens: int) -> None:
        self._goodput_t.observe(finish_t)
        self._goodput_tokens.observe(float(tokens))

    # -- rolling views -------------------------------------------------------

    def goodput_tokens_per_s(self, now: Optional[float] = None) -> float:
        ts = self._goodput_t.values()
        if ts.size == 0:
            return 0.0
        end = float(ts[-1]) if now is None else float(now)
        span = end - float(ts[0])
        if span <= 0.0:
            return 0.0
        return float(self._goodput_tokens.values().sum()) / span

    def violations(self) -> Dict[str, int]:
        return {k: int(c.value) for k, c in self._violations.items()}

    def publish(self, now: Optional[float] = None) -> None:
        """Refresh the rolling-view gauges from the live windows (the
        per-iteration sampler's hook)."""
        for kind, win in (("ttft", self.ttft_window), ("itl", self.itl_window)):
            pct = win.percentiles(_PCTS)
            for p in _PCTS:
                self._gauges[(kind, p)].set(round(pct[p], 6))
        self._goodput_gauge.set(round(self.goodput_tokens_per_s(now), 6))

    def snapshot(self) -> Dict[str, object]:
        """The SLO view as one dict — bench artifacts embed it."""
        return {
            "ttft_ms": {
                f"p{p}": round(v, 3)
                for p, v in self.ttft_window.percentiles(_PCTS).items()
            },
            "itl_ms": {
                f"p{p}": round(v, 3)
                for p, v in self.itl_window.percentiles(_PCTS).items()
            },
            "violations": self.violations(),
            "thresholds_ms": {"ttft": self.ttft_ms, "itl": self.itl_ms},
            "goodput_tokens_per_s": round(self.goodput_tokens_per_s(), 3),
            "window": self.ttft_window.size,
            "ttft_observations": self.ttft_window.total,
            "itl_observations": self.itl_window.total,
        }
