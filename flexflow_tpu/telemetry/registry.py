"""Metrics registry: counters, gauges, fixed-bucket histograms.

FlexFlow's defining loop is *measure, then decide* — the simulator is
calibrated from profiled kernels before the search commits to a
strategy. The serving stack makes the same kind of decisions at
iteration granularity (admission, preemption, draft length), so it
needs the same posture at runtime: every decision input is a metric
something can read back. This module is the storage layer — the
instrument points live in serving/, the thresholds in telemetry/slo.py.

Three metric kinds, Prometheus semantics:

* `Counter` — monotone accumulator (`inc`). Mirroring pre-counted host
  ledgers (a `FaultInjector.injected` Counter, a per-request drop
  count) goes through `set_monotonic`, which enforces the monotone
  contract instead of trusting the caller.
* `Gauge` — point-in-time value (`set`/`inc`/`dec`): page occupancy,
  queue depth, in-flight pinned pages.
* `Histogram` — FIXED buckets chosen at creation (`observe` is a
  bisect + two adds — no allocation, no resort). Exposition renders
  the cumulative `_bucket`/`_sum`/`_count` family; `percentile`
  interpolates within a bucket, the standard histogram_quantile
  estimate (the EXACT rolling percentiles live in slo.RollingWindow —
  the histogram is the unbounded-horizon aggregate, the window the SLO
  view).

Labels are first-class but deliberately minimal: a metric instance is
keyed by (name, sorted label items), e.g. the chaos ledger
`serve_fault_injections_total{site="nan"}`.

Two export surfaces:

* `render_prometheus()` — the text exposition format (`--metrics-out`),
  scrapeable or diffable.
* `sample()` — one flat `{series: value}` dict per call, the row format
  the per-iteration JSONL time series (`--metrics-jsonl`) streams; a
  `JsonlWriter` appends rows as they are taken so a long-running server
  never buffers the series in memory.

Everything here is stdlib-only and import-light: serving's hot path
touches metric objects, so they are __slots__ classes whose update cost
is an attribute add — near-zero against a jitted step dispatch, zero
when telemetry is disabled (the scheduler then never calls in).
"""

from __future__ import annotations

import json
import re
from bisect import bisect_left
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "JsonlWriter",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "DURABILITY_METRICS",
    "register_durability_metrics",
    "series_name",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default histogram buckets for millisecond latencies (TTFT,
#: inter-token): roughly log-spaced from sub-ms to minutes, the range a
#: CPU smoke test and a TPU pod both land inside.
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000,
)


#: the durability-layer metric catalog (journal / recovery / overload
#: protection — docs/resilience.md): name -> (kind, help, label key or
#: None). A checked-in contract rather than ad-hoc instrument-point
#: names, because recovery metrics are exactly the ones read AFTER a
#: crash, when nobody can ask the dead process what it called them;
#: `register_durability_metrics` pre-creates them so a freshly
#: restarted server's exposition shows explicit zeros, and
#: `validate.validate_durability_metrics` gates any sample row against
#: this table.
DURABILITY_METRICS: Dict[str, Tuple[str, str, Optional[str]]] = {
    "serve_recovery_total": (
        "counter",
        "crash-restart recoveries: journals replayed into a fresh engine",
        None,
    ),
    "serve_replayed_tokens_total": (
        "counter",
        "journal-committed tokens re-seeded into recovered requests",
        None,
    ),
    "serve_journal_bytes": (
        "gauge",
        "bytes appended to the write-ahead request journal",
        None,
    ),
    "serve_shed_total": (
        "counter",
        "requests shed at admission by the overload guard",
        "class",
    ),
    "serve_breaker_open_total": (
        "counter",
        "per-replica circuit-breaker open transitions",
        "replica",
    ),
}


def register_durability_metrics(
    registry: "MetricsRegistry",
    classes: Sequence[str] = ("default",),
    replicas: Sequence[object] = (),
) -> Dict[str, object]:
    """Pre-create every durability series in `registry` so a restarted
    server's first scrape shows explicit zeros (absent-vs-zero is the
    difference between 'no recovery happened' and 'nobody instrumented
    it'). Unlabelled metrics register bare; the labelled families get
    one series per entry of `classes` / `replicas`. Returns the
    created instances keyed by their flat series name."""
    out: Dict[str, object] = {}
    for name, (kind, help, label) in DURABILITY_METRICS.items():
        make = registry.counter if kind == "counter" else registry.gauge
        if label is None:
            out[name] = make(name, help=help)
        else:
            values = classes if label == "class" else replicas
            for v in values:
                labels = {label: str(v)}
                out[series_name(name, labels)] = make(
                    name, help=help, labels=labels
                )
    return out


def series_name(name: str, labels: Optional[Mapping[str, str]]) -> str:
    """`name{k="v",...}` — the flat key a JSONL row / sample dict uses
    for a labelled series (label order is sorted, so the key is
    stable)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotone accumulator."""

    kind = "counter"
    __slots__ = ("name", "help", "labels", "value")

    def __init__(self, name: str, help: str = "", labels=None):
        self.name = name
        self.help = help
        self.labels = labels
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: inc({amount}) < 0")
        self.value += amount

    def set_monotonic(self, value: float) -> None:
        """Mirror an externally-counted monotone ledger (e.g.
        FaultInjector.injected): the new value may equal but never
        undercut the current one."""
        if value < self.value:
            raise ValueError(
                f"counter {self.name}: set_monotonic({value}) would "
                f"decrease from {self.value}"
            )
        self.value = value


class Gauge:
    """Point-in-time value; goes up and down."""

    kind = "gauge"
    __slots__ = ("name", "help", "labels", "value")

    def __init__(self, name: str, help: str = "", labels=None):
        self.name = name
        self.help = help
        self.labels = labels
        self.value = 0.0

    def set(self, value) -> None:
        self.value = value

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class Histogram:
    """Fixed-bucket histogram. `bounds` are the finite upper bounds,
    strictly increasing; observations above the last bound land in the
    implicit +Inf bucket. `observe` is O(log buckets) with zero
    allocation — hot-path safe."""

    kind = "histogram"
    __slots__ = ("name", "help", "labels", "bounds", "counts", "sum", "count")

    def __init__(
        self,
        name: str,
        bounds: Sequence[float],
        help: str = "",
        labels=None,
    ):
        bounds = tuple(float(b) for b in bounds)
        if not bounds:
            raise ValueError(f"histogram {name}: needs at least one bucket")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(
                f"histogram {name}: bounds must be strictly increasing"
            )
        self.name = name
        self.help = help
        self.labels = labels
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def percentile(self, pct: float) -> float:
        """histogram_quantile-style estimate: find the bucket holding
        the pct-th observation and interpolate linearly inside it. The
        +Inf bucket clamps to the last finite bound (same convention as
        Prometheus). 0.0 with no observations."""
        if not self.count:
            return 0.0
        rank = pct / 100.0 * self.count
        cum = 0
        lo = 0.0
        for i, c in enumerate(self.counts):
            if not c:
                lo = self.bounds[i] if i < len(self.bounds) else lo
                continue
            if cum + c >= rank:
                if i >= len(self.bounds):
                    return self.bounds[-1]
                hi = self.bounds[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            cum += c
            lo = self.bounds[i] if i < len(self.bounds) else lo
        return self.bounds[-1]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create store for metric instances, keyed by
    (name, labels). One registry per Telemetry facade; SchedulerStats
    binds its fields to gauges in the same registry, so the exported
    text IS the stats surface."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._help: Dict[str, str] = {}
        self._kind: Dict[str, str] = {}

    # -- get-or-create -------------------------------------------------------

    def _get(self, cls, name, help, labels, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labels = dict(labels) if labels else None
        if labels:
            for k in labels:
                if not _LABEL_RE.match(k):
                    raise ValueError(f"invalid label name {k!r}")
            labels = {k: str(v) for k, v in labels.items()}
        key = (name, tuple(sorted(labels.items())) if labels else ())
        existing = self._metrics.get(key)
        if existing is not None:
            if existing.kind != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{existing.kind}, not {cls.kind}"
                )
            return existing
        if name in self._kind and self._kind[name] != cls.kind:
            raise ValueError(
                f"metric family {name!r} already registered as "
                f"{self._kind[name]}, not {cls.kind}"
            )
        metric = cls(name, help=help, labels=labels, **kw)
        self._metrics[key] = metric
        self._kind[name] = cls.kind
        if help and name not in self._help:
            self._help[name] = help
        return metric

    def counter(self, name: str, help: str = "", labels=None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels=None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS,
        help: str = "",
        labels=None,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels, bounds=bounds)

    # -- introspection -------------------------------------------------------

    def metrics(self) -> List[object]:
        """All metric instances, sorted by (name, labels) — the
        deterministic order both exporters render in."""
        return [self._metrics[k] for k in sorted(self._metrics)]

    def get(self, name: str, labels=None):
        """The metric instance, or None — for tests and assertions."""
        labels = {k: str(v) for k, v in labels.items()} if labels else None
        key = (name, tuple(sorted(labels.items())) if labels else ())
        return self._metrics.get(key)

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4): HELP/TYPE
        headers once per family, then one sample line per series;
        histograms expand to the cumulative _bucket/_sum/_count
        family."""
        lines: List[str] = []
        seen_header = set()
        for m in self.metrics():
            if m.name not in seen_header:
                seen_header.add(m.name)
                if self._help.get(m.name):
                    lines.append(f"# HELP {m.name} {self._help[m.name]}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            if m.kind == "histogram":
                cum = 0
                for bound, c in zip(m.bounds, m.counts):
                    cum += c
                    lbl = dict(m.labels or {})
                    lbl["le"] = _fmt(bound)
                    lines.append(
                        f"{series_name(m.name + '_bucket', lbl)} {cum}"
                    )
                lbl = dict(m.labels or {})
                lbl["le"] = "+Inf"
                lines.append(
                    f"{series_name(m.name + '_bucket', lbl)} {m.count}"
                )
                lines.append(
                    f"{series_name(m.name + '_sum', m.labels)} "
                    f"{_fmt(m.sum)}"
                )
                lines.append(
                    f"{series_name(m.name + '_count', m.labels)} {m.count}"
                )
            else:
                lines.append(
                    f"{series_name(m.name, m.labels)} {_fmt(m.value)}"
                )
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.render_prometheus())

    def sample(self, **extra) -> Dict[str, object]:
        """One flat {series: value} snapshot — the JSONL row shape.
        Histograms contribute their _count/_sum (the series a
        time-series consumer can rate()); `extra` keys (iteration
        number, wall time) ride along verbatim."""
        row: Dict[str, object] = dict(extra)
        for m in self.metrics():
            if m.kind == "histogram":
                row[series_name(m.name + "_count", m.labels)] = m.count
                row[series_name(m.name + "_sum", m.labels)] = round(
                    m.sum, 9
                )
            else:
                v = m.value
                row[series_name(m.name, m.labels)] = (
                    round(v, 9) if isinstance(v, float) else v
                )
        return row


def _fmt(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class JsonlWriter:
    """Streams sample rows to a JSONL file as they are taken — no
    in-memory buffering of the series, so a long-running server's
    telemetry footprint stays flat. The file opens lazily on the first
    row and closes at `close()` (idempotent)."""

    def __init__(self, path: str):
        self.path = path
        self.rows_written = 0
        self._f = None

    def write(self, row: Mapping[str, object]) -> None:
        if self._f is None:
            # truncate on the FIRST open only: a write after close()
            # (flush mid-run, then more iterations) appends
            self._f = open(self.path, "w" if not self.rows_written else "a")
        self._f.write(json.dumps(row, sort_keys=True) + "\n")
        self.rows_written += 1

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
