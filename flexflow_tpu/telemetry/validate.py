"""Validators for the telemetry export formats.

Three artifact formats leave this package, and each has a checked-in
contract CI gates on:

* **Chrome trace-event JSON** (`--trace`) — `schemas/trace.schema.json`
  describes the document shape; `validate_trace` additionally enforces
  the semantic rules a schema language cannot: per-lane spans properly
  nest (contained or disjoint — what Perfetto's stacking assumes), no
  negative durations, and timestamps are finite.
* **metrics JSONL** (`--metrics-jsonl`) —
  `schemas/metrics_jsonl.schema.json`: every row is a flat object of
  numeric series keyed by `name{label="v"}` plus the `iteration`/`t_s`
  sample coordinates.
* **Prometheus text** (`--metrics-out`) — a line grammar, not JSON, so
  `validate_metrics_text` checks it directly: HELP/TYPE headers,
  sample-line syntax, histogram `_bucket` cumulativity ending at the
  `_count` value.
* **search trace JSONL** (`--search-trace`) —
  `schemas/search_trace.schema.json` per row (negative costs are a
  schema violation), plus the semantics: the header comes first,
  candidate ids are strictly increasing (out-of-order ids mean the
  recorder — or a hand-edited artifact — lied about consideration
  order), and at most one result record closes the stream.

The schema checker is a deliberate subset of JSON Schema (type,
required, properties, additionalProperties, items, enum, minimum) —
enough to express the checked-in contracts without adding a dependency
the container doesn't have.

All validators raise `ValidationError` with a path-qualified message;
`errors="list"` collects instead (the bench gate reports all findings
at once).
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Mapping, Optional, Sequence

__all__ = [
    "ValidationError",
    "load_schema",
    "check_schema",
    "validate_trace",
    "validate_metrics_jsonl",
    "validate_metrics_text",
    "validate_search_trace",
    "validate_search_trace_file",
    "validate_durability_metrics",
]

SCHEMA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "schemas")


class ValidationError(ValueError):
    """A telemetry artifact violates its checked-in contract."""


def load_schema(name: str) -> dict:
    """A checked-in schema by file name (e.g. 'trace.schema.json')."""
    with open(os.path.join(SCHEMA_DIR, name)) as f:
        return json.load(f)


# -- subset-of-JSON-Schema checker --------------------------------------------

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "null": type(None),
}


def _type_ok(value, tname: str) -> bool:
    if tname == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if tname == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, _TYPES[tname])


def check_schema(value, schema: Mapping, path: str = "$") -> List[str]:
    """Errors (empty = valid) for `value` against the schema subset."""
    errs: List[str] = []
    t = schema.get("type")
    if t is not None:
        types = t if isinstance(t, list) else [t]
        if not any(_type_ok(value, name) for name in types):
            return [f"{path}: expected {t}, got {type(value).__name__}"]
    if "enum" in schema and value not in schema["enum"]:
        errs.append(f"{path}: {value!r} not in enum {schema['enum']}")
    if "minimum" in schema and isinstance(value, (int, float)) and not (
        isinstance(value, bool)
    ):
        if value < schema["minimum"]:
            errs.append(
                f"{path}: {value} below minimum {schema['minimum']}"
            )
    if isinstance(value, dict):
        for req in schema.get("required", ()):
            if req not in value:
                errs.append(f"{path}: missing required key {req!r}")
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties", True)
        for k, v in value.items():
            if k in props:
                errs.extend(check_schema(v, props[k], f"{path}.{k}"))
            elif addl is False:
                errs.append(f"{path}: unexpected key {k!r}")
            elif isinstance(addl, dict):
                errs.extend(check_schema(v, addl, f"{path}.{k}"))
    if isinstance(value, list) and "items" in schema:
        for i, item in enumerate(value):
            errs.extend(check_schema(item, schema["items"], f"{path}[{i}]"))
    return errs


def _raise_or_return(errs: List[str], errors: str) -> List[str]:
    if errs and errors == "raise":
        raise ValidationError("; ".join(errs[:20]))
    return errs


# -- trace validation ---------------------------------------------------------


def validate_trace(doc: Mapping, errors: str = "raise") -> List[str]:
    """Schema + semantics for a trace-event document: every event
    matches the checked-in schema; 'X' spans have finite ts and
    non-negative dur; spans sharing a (pid, tid) lane properly nest
    (for any two spans, disjoint or one contains the other)."""
    errs = check_schema(doc, load_schema("trace.schema.json"))
    if errs:
        return _raise_or_return(errs, errors)
    lanes: Dict[tuple, List[tuple]] = {}
    for i, ev in enumerate(doc["traceEvents"]):
        ph = ev.get("ph")
        if ph == "X":
            ts, dur = ev["ts"], ev["dur"]
            if dur < 0:
                errs.append(f"event[{i}] {ev.get('name')!r}: negative dur {dur}")
                continue
            lanes.setdefault((ev["pid"], ev["tid"]), []).append(
                (float(ts), float(ts) + float(dur), i, ev.get("name"))
            )
    eps = 1e-3  # trace ts are rounded to 1e-3 us — tolerate the rounding
    for lane, spans in lanes.items():
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: List[tuple] = []
        for start, end, i, name in spans:
            while stack and start >= stack[-1][1] - eps:
                stack.pop()
            if stack and end > stack[-1][1] + eps:
                errs.append(
                    f"lane pid={lane[0]} tid={lane[1]}: span "
                    f"{name!r} [{start}, {end}] partially overlaps "
                    f"{stack[-1][3]!r} [{stack[-1][0]}, {stack[-1][1]}] "
                    "— spans on one lane must nest"
                )
                continue
            stack.append((start, end, i, name))
    return _raise_or_return(errs, errors)


def validate_trace_file(path: str, errors: str = "raise") -> List[str]:
    with open(path) as f:
        return validate_trace(json.load(f), errors=errors)


# -- metrics JSONL validation -------------------------------------------------


#: the labelled-series key grammar (registry.series_name's output):
#: `name{k="v",...}` — pairs sorted, values quoted. Multi-tenant
#: serving keys all the per-class/per-tenant series this way
#: (serve_requests_total{class="gold"}, serve_ttft_ms_p95{tenant=...}).
_LABELLED_KEY_RE = re.compile(
    r'^[A-Za-z_:][A-Za-z0-9_:]*'
    r'\{[A-Za-z_][A-Za-z0-9_]*="[^"{}]*"'
    r'(,[A-Za-z_][A-Za-z0-9_]*="[^"{}]*")*\}$'
)


def validate_metrics_jsonl(
    lines: Sequence[str], errors: str = "raise"
) -> List[str]:
    """Every row parses and matches the row schema; `iteration` is
    non-decreasing (it is a time series, not a bag); every braced
    series key matches the labelled grammar `name{k="v",...}` the
    registry emits (the tenant/class-labelled serving series are the
    main producer)."""
    schema = load_schema("metrics_jsonl.schema.json")
    errs: List[str] = []
    last_iter: Optional[int] = None
    for n, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError as e:
            errs.append(f"line {n + 1}: not JSON ({e})")
            continue
        errs.extend(
            f"line {n + 1}: {e}" for e in check_schema(row, schema)
        )
        for k in row:
            if "{" in k and not _LABELLED_KEY_RE.match(k):
                errs.append(
                    f"line {n + 1}: series key {k!r} does not match "
                    'the labelled grammar name{k="v",...}'
                )
        it = row.get("iteration")
        if isinstance(it, int):
            if last_iter is not None and it < last_iter:
                errs.append(
                    f"line {n + 1}: iteration {it} < previous {last_iter}"
                )
            last_iter = it
    return _raise_or_return(errs, errors)


def validate_metrics_jsonl_file(path: str, errors: str = "raise") -> List[str]:
    with open(path) as f:
        return validate_metrics_jsonl(f.readlines(), errors=errors)


# -- durability metric contract -----------------------------------------------

_SERIES_KEY_RE = re.compile(
    r'^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?$'
)


def validate_durability_metrics(
    sample: Mapping, errors: str = "raise", require_all: bool = False
) -> List[str]:
    """The durability-layer series in a flat sample row (a
    `MetricsRegistry.sample()` dict or a parsed `--metrics-jsonl` row)
    match the checked-in `registry.DURABILITY_METRICS` catalog:
    unlabelled metrics appear bare, the labelled families
    (`serve_shed_total{class=...}`, `serve_breaker_open_total
    {replica=...}`) carry exactly their catalog label key, and every
    value is a non-negative number (counters and byte gauges both only
    accumulate). `require_all=True` additionally demands every
    unlabelled series be present — the post-`register_durability_
    metrics` contract, where a fresh server exposes explicit zeros so
    'no recovery happened' is distinguishable from 'nobody
    instrumented it'."""
    from flexflow_tpu.telemetry.registry import DURABILITY_METRICS

    errs: List[str] = []
    seen = set()
    for key, value in sample.items():
        m = _SERIES_KEY_RE.match(key)
        if m is None:
            continue
        name = m.group("name")
        if name not in DURABILITY_METRICS:
            continue
        seen.add(name)
        _kind, _help, label = DURABILITY_METRICS[name]
        labels = m.group("labels")
        if label is None and labels is not None:
            errs.append(
                f"{key!r}: {name} is unlabelled in the durability "
                f"catalog but the series carries labels"
            )
        elif label is not None:
            keys = [
                p.split("=", 1)[0]
                for p in (labels.split(",") if labels else [])
            ]
            if keys != [label]:
                errs.append(
                    f"{key!r}: {name} must carry exactly the "
                    f"{label!r} label, got {keys}"
                )
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            errs.append(f"{key!r}: non-numeric value {value!r}")
        elif value < 0:
            errs.append(
                f"{key!r}: negative value {value} — durability "
                "series only accumulate"
            )
    if require_all:
        for name, (_k, _h, label) in DURABILITY_METRICS.items():
            if label is None and name not in seen:
                errs.append(
                    f"missing durability series {name!r} — "
                    "register_durability_metrics pre-creates it so a "
                    "fresh server exposes an explicit zero"
                )
    return _raise_or_return(errs, errors)


# -- search trace JSONL validation --------------------------------------------


def validate_search_trace(
    lines: Sequence[str], errors: str = "raise"
) -> List[str]:
    """Every row parses and matches the search-trace schema (costs are
    non-negative by schema `minimum`); the first row is the header;
    candidate `id`s are strictly increasing; at most one `result`."""
    schema = load_schema("search_trace.schema.json")
    errs: List[str] = []
    last_id: Optional[int] = None
    saw_rows = 0
    results = 0
    for n, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError as e:
            errs.append(f"line {n + 1}: not JSON ({e})")
            continue
        errs.extend(
            f"line {n + 1}: {e}" for e in check_schema(row, schema)
        )
        if not isinstance(row, dict):
            continue
        saw_rows += 1
        rtype = row.get("type")
        if saw_rows == 1 and rtype != "header":
            errs.append(
                f"line {n + 1}: first record must be the header, "
                f"got {rtype!r}"
            )
        if rtype == "candidate":
            cid = row.get("id")
            if isinstance(cid, int):
                if last_id is not None and cid <= last_id:
                    errs.append(
                        f"line {n + 1}: candidate id {cid} out of order "
                        f"(previous {last_id}) — consideration order is "
                        "the artifact's contract"
                    )
                last_id = cid
        elif rtype == "result":
            results += 1
            if results > 1:
                errs.append(
                    f"line {n + 1}: more than one result record"
                )
    if saw_rows == 0:
        errs.append("empty search trace (no records)")
    return _raise_or_return(errs, errors)


def validate_search_trace_file(path: str, errors: str = "raise") -> List[str]:
    with open(path) as f:
        return validate_search_trace(f.readlines(), errors=errors)


# -- Prometheus text validation -----------------------------------------------

_HELP_RE = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram|summary|untyped)$"
)
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" (-?[0-9.eE+-]+|NaN|\+Inf|-Inf)$"
)
_LE_RE = re.compile(r'le="([^"]*)"')


def validate_metrics_text(text: str, errors: str = "raise") -> List[str]:
    """Prometheus exposition grammar + histogram semantics: every line
    is a HELP/TYPE header or a sample; every sampled family has a TYPE;
    `_bucket` series are cumulative and end at the family's `_count`."""
    errs: List[str] = []
    typed: Dict[str, str] = {}
    buckets: Dict[str, List[tuple]] = {}
    counts: Dict[str, float] = {}
    for n, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            if not _HELP_RE.match(line):
                errs.append(f"line {n + 1}: malformed HELP line")
            continue
        if line.startswith("# TYPE "):
            m = _TYPE_RE.match(line)
            if not m:
                errs.append(f"line {n + 1}: malformed TYPE line")
            else:
                typed[m.group(1)] = m.group(2)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errs.append(f"line {n + 1}: malformed sample line {line!r}")
            continue
        name, labels = m.group(1), m.group(2) or ""
        value = float(m.group(4).replace("+Inf", "inf").replace("-Inf", "-inf"))
        family = re.sub(r"_(bucket|sum|count)$", "", name)
        if family not in typed and name not in typed:
            errs.append(f"line {n + 1}: sample {name!r} has no TYPE header")
        if name.endswith("_bucket"):
            le = _LE_RE.search(labels)
            if le is None:
                errs.append(f"line {n + 1}: _bucket sample without le label")
            else:
                bound = float(le.group(1).replace("+Inf", "inf"))
                buckets.setdefault(
                    family + _labels_without_le(labels), []
                ).append((bound, value))
        elif name.endswith("_count"):
            counts[family + labels] = value
    for key, series in buckets.items():
        series.sort()
        vals = [v for _, v in series]
        if any(prev > nxt for prev, nxt in zip(vals, vals[1:])):
            errs.append(f"{key}: _bucket series is not cumulative")
        if series and series[-1][0] != float("inf"):
            errs.append(f"{key}: missing le=\"+Inf\" bucket")
        total = counts.get(key)
        if series and total is not None and vals[-1] != total:
            errs.append(
                f"{key}: +Inf bucket {vals[-1]} != _count {total}"
            )
    return _raise_or_return(errs, errors)


_LABEL_PAIR_RE = re.compile(r'[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"')


def _labels_without_le(labels: str) -> str:
    rest = [
        p for p in _LABEL_PAIR_RE.findall(labels) if not p.startswith("le=")
    ]
    return "{" + ",".join(rest) + "}" if rest else ""
