"""Trace layer: Chrome trace-event JSON, loadable in Perfetto.

The async double-buffered engine's whole value proposition is a timing
shape — dispatch N+1 runs while step N is still on the device — and a
scalar (`overlap_fraction`) can report that shape but never *show* it.
This tracer records spans the way a profiler would and exports the
Chrome trace-event format (`--trace PATH`), so `chrome://tracing` or
https://ui.perfetto.dev renders the pipeline: host lanes carrying the
iteration/dispatch/reconcile spans, device lanes carrying each step's
in-flight window, request lanes carrying the QUEUED→RUNNING→terminal
lifecycle rebuilt from the per-request `events` audit log.

Span discipline: every span on one (pid, tid) lane must properly nest
(contained or disjoint — the renderer draws a stack per lane). The
in-flight windows of consecutive async steps deliberately OVERLAP in
time, so they alternate between two device lanes by step parity —
each lane nests trivially, and the overlap is visible as two staggered
rows, exactly the double-buffer picture. `validate.validate_trace`
enforces the discipline (plus non-negative durations) and the CI smoke
runs it over a real exported trace.

Timestamps are `time.perf_counter()` seconds relative to the tracer's
construction, exported as microseconds (the trace-event unit). All
recording methods are allocation-light appends; the NullTracer twin in
__init__.py makes every call a no-op when tracing is off.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Dict, List, Mapping, Optional

__all__ = ["Tracer", "PID_ENGINE", "PID_REQUESTS", "TID_HOST", "TID_DEVICE0"]

#: process lanes: engine timeline vs per-request lifecycle
PID_ENGINE = 1
PID_REQUESTS = 2

#: thread lanes inside the engine process
TID_HOST = 1  # scheduler host work: iterations, dispatch, reconcile
TID_DEVICE0 = 10  # in-flight device windows, even steps
TID_DEVICE1 = 11  # in-flight device windows, odd steps (overlap lane)
TID_HOST_BASE = 20  # per-host-partition lanes (pod serving), 20 + host
TID_REPLICA_BASE = 200  # per-engine-replica lanes (front door), 200 + idx


class Tracer:
    """Append-only trace-event recorder."""

    def __init__(self, max_events: int = 1_000_000):
        self.t0 = time.perf_counter()
        self.events: List[dict] = []
        self.dropped_events = 0
        self.max_events = int(max_events)
        self._host_lanes: set = set()
        self._meta(PID_ENGINE, None, "process_name", "flexflow_tpu.serve")
        self._meta(PID_ENGINE, TID_HOST, "thread_name", "host scheduler")
        self._meta(PID_ENGINE, TID_DEVICE0, "thread_name", "device in-flight (even)")
        self._meta(PID_ENGINE, TID_DEVICE1, "thread_name", "device in-flight (odd)")
        self._meta(PID_REQUESTS, None, "process_name", "requests")

    # -- low level -----------------------------------------------------------

    def now(self) -> float:
        return time.perf_counter()

    def _us(self, t: float) -> float:
        return round((t - self.t0) * 1e6, 3)

    def _meta(self, pid: int, tid: Optional[int], name: str, value: str):
        ev = {
            "ph": "M",
            "name": name,
            "pid": pid,
            "args": {"name": value},
        }
        if tid is not None:
            ev["tid"] = tid
        self.events.append(ev)

    def _push(self, ev: dict) -> None:
        # bounded like the request audit log: a runaway trace drops
        # (and counts) rather than eating the host
        if len(self.events) >= self.max_events:
            self.dropped_events += 1
            return
        self.events.append(ev)

    def host_lane(self, host: int) -> int:
        """The engine-process lane for one host partition of a pod
        placement (serving/distributed.py). Lanes register their
        thread_name metadata on first use so the Perfetto UI labels
        them; events land via complete(..., tid=host_lane(h))."""
        tid = TID_HOST_BASE + int(host)
        if tid not in self._host_lanes:
            self._host_lanes.add(tid)
            self._meta(
                PID_ENGINE, tid, "thread_name", f"host {int(host)} partition"
            )
        return tid

    def replica_lane(self, replica: int) -> int:
        """The engine-process lane for one front-door engine replica
        (serving/frontend/router.py) — same registration discipline as
        host_lane, offset past the host range so a routed pod placement
        keeps both label families distinct."""
        tid = TID_REPLICA_BASE + int(replica)
        if tid not in self._host_lanes:
            self._host_lanes.add(tid)
            self._meta(
                PID_ENGINE, tid, "thread_name", f"replica {int(replica)}"
            )
        return tid

    # -- recording -----------------------------------------------------------

    def complete(
        self,
        name: str,
        cat: str,
        start_s: float,
        end_s: float,
        pid: int = PID_ENGINE,
        tid: int = TID_HOST,
        args: Optional[Mapping[str, object]] = None,
    ) -> None:
        """One 'X' (complete) event: a span [start_s, end_s] in tracer
        clock seconds. Zero-length spans are legal; negative ones are
        the caller's bug and clamp to zero so a clock hiccup can never
        make the export invalid."""
        dur = max(0.0, end_s - start_s)
        ev = {
            "ph": "X",
            "name": name,
            "cat": cat,
            "ts": self._us(start_s),
            "dur": round(dur * 1e6, 3),
            "pid": pid,
            "tid": tid,
        }
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def instant(
        self,
        name: str,
        cat: str,
        t_s: Optional[float] = None,
        pid: int = PID_ENGINE,
        tid: int = TID_HOST,
        args: Optional[Mapping[str, object]] = None,
    ) -> None:
        ev = {
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "name": name,
            "cat": cat,
            "ts": self._us(self.now() if t_s is None else t_s),
            "pid": pid,
            "tid": tid,
        }
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    @contextmanager
    def span(
        self,
        name: str,
        cat: str = "host",
        pid: int = PID_ENGINE,
        tid: int = TID_HOST,
        args: Optional[Mapping[str, object]] = None,
    ):
        """Context-managed complete event around a host code block."""
        t0 = self.now()
        try:
            yield
        finally:
            self.complete(name, cat, t0, self.now(), pid=pid, tid=tid,
                          args=args)

    def device_window(
        self, kind: str, step_index: int, start_s: float, end_s: float,
        args: Optional[Mapping[str, object]] = None,
    ) -> None:
        """One in-flight window (dispatch → reconcile-complete) on a
        device lane. Consecutive async windows overlap in time by
        design, so they alternate lanes by step parity — each lane
        nests, and the overlap reads as the staggered double-buffer."""
        a = {"step": int(step_index), "kind": kind}
        if args:
            a.update(args)
        self.complete(
            f"inflight:{kind}",
            "device",
            start_s,
            end_s,
            tid=TID_DEVICE0 if step_index % 2 == 0 else TID_DEVICE1,
            args=a,
        )

    # -- request lifecycle ---------------------------------------------------

    def request_lifecycle(self, req) -> None:
        """Rebuild a terminal request's phase spans from its `events`
        audit log (serving/scheduler.Request.log): QUEUED from
        submit→admit, RUNNING from admit→preempt/terminal, one span per
        re-admission after preemption, instants for first_token and
        preempt, and the terminal status on the closing span's args.
        The log is a ring buffer — a truncated front (dropped events)
        starts the rebuild at the first surviving event."""
        if not req.events:
            return
        tid = int(req.rid)
        self._meta(PID_REQUESTS, tid, "thread_name", f"request {req.rid}")
        phase: Optional[str] = None
        phase_t = 0.0
        last_t = 0.0

        def close(end_t: float, status: Optional[str] = None) -> None:
            nonlocal phase
            if phase is None:
                return
            args = {"rid": int(req.rid)}
            if status:
                args["status"] = status
                args["tokens"] = len(req.generated)
            self.complete(phase, "request", phase_t, end_t,
                          pid=PID_REQUESTS, tid=tid, args=args)
            phase = None

        for t, name, detail in list(req.events):
            last_t = t
            if name == "submit":
                phase, phase_t = "QUEUED", t
            elif name == "admit":
                close(t)
                phase, phase_t = "RUNNING", t
            elif name == "preempt":
                self.instant("preempt", "request", t, pid=PID_REQUESTS,
                             tid=tid, args={"rid": int(req.rid)})
                close(t)
                phase, phase_t = "QUEUED", t
            elif name == "first_token":
                self.instant("first_token", "request", t,
                             pid=PID_REQUESTS, tid=tid,
                             args={"rid": int(req.rid)})
            else:
                # terminal statuses close whatever phase is open
                close(t, status=name)
        close(last_t, status=req.status)

    # -- export --------------------------------------------------------------

    def to_json(self) -> Dict[str, object]:
        doc = {
            "traceEvents": list(self.events),
            "displayTimeUnit": "ms",
        }
        if self.dropped_events:
            doc["droppedEvents"] = self.dropped_events
        return doc

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f)
            f.write("\n")
