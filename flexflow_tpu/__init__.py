"""flexflow_tpu: a TPU-native automatic-parallelization training framework.

A from-scratch rebuild of the capabilities of FlexFlow/Unity (reference:
daiyaanarfeen/FlexFlow; see SURVEY.md) designed for TPU: the model-builder
API produces a Parallel Computation Graph, `compile()` searches over
substitutions and per-op mesh placements with a calibrated cost model, and
the chosen strategy executes as one jitted XLA program with GSPMD shardings
over an ICI mesh.
"""

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.machine import MachineResource, MachineSpec, MachineView
from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.types import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    ParameterSyncType,
)
from flexflow_tpu.runtime.executor import MeshConfig
from flexflow_tpu.runtime.initializer import (
    ConstantInitializer,
    GlorotUniform,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)
from flexflow_tpu.runtime.model import FFModel, Tensor
from flexflow_tpu.runtime.optimizer import AdamOptimizer, SGDOptimizer
from flexflow_tpu.runtime.recompile import RecompileState
from flexflow_tpu.serving.api import ServeConfig

__version__ = "0.2.0"

__all__ = [
    "FFConfig",
    "FFModel",
    "Tensor",
    "DataType",
    "OperatorType",
    "ActiMode",
    "AggrMode",
    "LossType",
    "MetricsType",
    "CompMode",
    "ParameterSyncType",
    "ParallelDim",
    "ParallelTensorShape",
    "MachineView",
    "MachineResource",
    "MachineSpec",
    "MeshConfig",
    "SGDOptimizer",
    "AdamOptimizer",
    "RecompileState",
    "ServeConfig",
    "GlorotUniform",
    "ZeroInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormInitializer",
]
