"""fxlint driver: file scan, rule selection, baseline compare, exit code.

``python -m flexflow_tpu.analysis [paths] [options]`` — see
docs/analysis.md. Exit 0 when every finding is baselined (or none),
1 when NEW findings exist, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from flexflow_tpu.analysis import dispatch_race, pallas_gate, retrace
from flexflow_tpu.analysis.diagnostics import (
    Diagnostic,
    baseline_key,
    collect_python_files,
    load_baseline,
    parse_files,
    write_baseline,
)

#: rule families: name -> (module, rule-id prefix)
FAMILIES = {
    "dispatch-race": (dispatch_race, "FX1"),
    "retrace-storm": (retrace, "FX2"),
    "pallas-gate": (pallas_gate, "FX4"),
}


def run_rules(
    paths: Sequence[str], families: Optional[Sequence[str]] = None
) -> List[Diagnostic]:
    """Run the AST rule families over `paths` (files or directories).
    `families` filters by family name or rule-id prefix; None runs all."""
    files = collect_python_files(paths)
    trees, diags = parse_files(files)
    selected = _select_families(families)
    for name in selected:
        module, _prefix = FAMILIES[name]
        diags.extend(module.run(trees))
    return sorted(diags, key=lambda d: (d.path, d.line, d.rule_id))


def _select_families(families: Optional[Sequence[str]]) -> List[str]:
    if not families:
        return list(FAMILIES)
    out = []
    for want in families:
        for name, (_module, prefix) in FAMILIES.items():
            if want == name or want.upper().startswith(prefix):
                if name not in out:
                    out.append(name)
                break
        else:
            raise SystemExit(
                f"fxlint: unknown rule family {want!r} "
                f"(known: {sorted(FAMILIES)})"
            )
    return out


def check_strategy_files(paths: Sequence[str]) -> List[Diagnostic]:
    """Replay the FX3xx strategy validator over exported strategy JSON
    files (search/strategy_io format)."""
    from flexflow_tpu.analysis.strategy_check import validate_strategy_doc

    diags: List[Diagnostic] = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            diags.append(
                Diagnostic("FX000", path, 1, f"unreadable strategy file: {e}")
            )
            continue
        for sd in validate_strategy_doc(doc):
            diags.append(
                Diagnostic(
                    sd.rule_id,
                    path,
                    1,
                    f"[{sd.node or 'mesh'}] {sd.message}",
                    severity=sd.severity,
                )
            )
    return diags


def _all_rule_docs() -> Dict[str, str]:
    from flexflow_tpu.analysis import strategy_check

    docs: Dict[str, str] = {"FX000": "unparseable file / unreadable input"}
    for module, _prefix in FAMILIES.values():
        docs.update(module.RULES)
    docs.update(strategy_check.RULES)
    return dict(sorted(docs.items()))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fxlint",
        description=(
            "Repo-specific static analysis: dispatch races, retrace "
            "storms, strategy invariants, Pallas geometry gates."
        ),
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: the flexflow_tpu package)",
    )
    ap.add_argument(
        "--baseline",
        default="fxlint_baseline.txt",
        help="baseline file of accepted findings (default: "
        "fxlint_baseline.txt)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: every finding counts as new",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to the current findings and exit 0",
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated rule families or id prefixes "
        "(dispatch-race,retrace-storm,pallas-gate / FX1,FX2,FX4)",
    )
    ap.add_argument(
        "--strategy",
        action="append",
        default=[],
        metavar="FILE",
        help="also replay the FX3xx strategy validator over an exported "
        "strategy JSON file (repeatable)",
    )
    ap.add_argument(
        "--show-baselined",
        action="store_true",
        help="print baselined findings too (marked), not just new ones",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, doc in _all_rule_docs().items():
            print(f"{rid}  {doc}")
        return 0

    paths = args.paths
    if not paths and not args.strategy:
        default = os.path.join(os.getcwd(), "flexflow_tpu")
        if not os.path.isdir(default):
            print(
                "fxlint: no paths given and ./flexflow_tpu not found "
                "(run from the repo root or pass paths)",
                file=sys.stderr,
            )
            return 2
        paths = [default]

    families = [f for f in args.rules.split(",") if f] or None
    diags: List[Diagnostic] = []
    if paths:
        diags.extend(run_rules(paths, families))
    diags.extend(check_strategy_files(args.strategy))

    if args.update_baseline:
        write_baseline(args.baseline, diags)
        print(
            f"fxlint: baseline {args.baseline} updated with "
            f"{len(diags)} finding(s)"
        )
        return 0

    baseline = (
        set() if args.no_baseline else load_baseline(args.baseline)
    )
    base_dir = os.path.dirname(os.path.abspath(args.baseline)) or "."
    new: List[Diagnostic] = []
    old: List[Diagnostic] = []
    for d in diags:
        (old if baseline_key(d, base_dir) in baseline else new).append(d)
    for d in new:
        print(d.format())
    if args.show_baselined:
        for d in old:
            print(f"{d.format()} (baselined)")
    print(
        f"fxlint: {len(new)} new finding(s), {len(old)} baselined"
    )
    return 1 if new else 0
