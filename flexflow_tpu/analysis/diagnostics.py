"""Diagnostic type, shared AST helpers, and the baseline workflow.

A finding prints as ``file:line rule-id message``. The baseline file
(one ``rule-id<TAB>path<TAB>message`` per line) records ACCEPTED
findings: the linter exits nonzero only on findings not in the
baseline, so CI fails on regressions without demanding a
fix-everything flag day. Baseline keys deliberately exclude the line
number — unrelated edits that shift a finding a few lines must not
break CI — and store paths relative to the baseline file's directory
so the key is stable regardless of the invoking cwd.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding. `severity` is "error" (counts toward the exit
    code) or "warning" (informational)."""

    rule_id: str
    path: str
    line: int
    message: str
    severity: str = "error"

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule_id} {self.message}"


def baseline_key(diag: Diagnostic, baseline_dir: str) -> Tuple[str, str, str]:
    """(rule, path-relative-to-baseline, message) — line-independent."""
    path = os.path.abspath(diag.path)
    try:
        rel = os.path.relpath(path, baseline_dir)
    except ValueError:  # different drive (windows)
        rel = path
    return (diag.rule_id, rel.replace(os.sep, "/"), diag.message)


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    keys: Set[Tuple[str, str, str]] = set()
    if not os.path.exists(path):
        return keys
    with open(path) as f:
        for raw in f:
            line = raw.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            parts = line.split("\t", 2)
            if len(parts) == 3:
                keys.add((parts[0], parts[1], parts[2]))
    return keys


def write_baseline(path: str, diagnostics: Iterable[Diagnostic]) -> None:
    base_dir = os.path.dirname(os.path.abspath(path)) or "."
    keys = sorted({baseline_key(d, base_dir) for d in diagnostics})
    with open(path, "w") as f:
        f.write(
            "# fxlint baseline — accepted findings "
            "(rule-id<TAB>path<TAB>message).\n"
            "# Regenerate with: python -m flexflow_tpu.analysis "
            "--update-baseline\n"
            "# CI fails on findings NOT listed here; fix the code or "
            "re-baseline deliberately.\n"
        )
        for k in keys:
            f.write("\t".join(k) + "\n")


# -- file collection / parsing ------------------------------------------------


def collect_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted .py file list, skipping
    caches and hidden directories."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [
                d
                for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            ]
            for name in files:
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return sorted(set(out))


def parse_files(
    files: Iterable[str],
) -> Tuple[Dict[str, ast.Module], List[Diagnostic]]:
    """path -> parsed module. Unparseable files become FX000 findings
    instead of crashing the lint run."""
    trees: Dict[str, ast.Module] = {}
    diags: List[Diagnostic] = []
    for path in files:
        try:
            with open(path, "rb") as f:
                src = f.read()
            trees[path] = ast.parse(src, filename=path)
        except (SyntaxError, ValueError, OSError) as e:
            line = getattr(e, "lineno", 1) or 1
            diags.append(
                Diagnostic("FX000", path, line, f"unparseable file: {e}")
            )
    return trees, diags


# -- shared AST helpers -------------------------------------------------------


def name_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Dotted-name chain of an expression: ``a.b.c`` -> ("a","b","c"),
    None for anything that is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def is_jit_call(node: ast.AST) -> bool:
    """A ``jax.jit(...)`` / ``jit(...)`` wrapper construction."""
    if not isinstance(node, ast.Call):
        return False
    chain = name_chain(node.func)
    return chain in (("jax", "jit"), ("jit",))


def collect_jitted_names(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """Names bound to a jit wrapper in this module, with their static
    argument positions: ``self._step = jax.jit(f, static_argnums=(1,))``
    yields {"_step": (1,)} (plain ``x = jax.jit(f)`` yields {"x": ()}).
    Keyed by the LAST chain element so attribute-held wrappers are
    recognized at ``self._step(...)`` call sites."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not is_jit_call(node.value):
            continue
        static: Tuple[int, ...] = ()
        for kw in node.value.keywords:
            if kw.arg != "static_argnums":
                continue
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, int
            ):
                static = (kw.value.value,)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                static = tuple(
                    e.value
                    for e in kw.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                )
        for target in node.targets:
            chain = name_chain(target)
            if chain:
                out[chain[-1]] = static
    return out
