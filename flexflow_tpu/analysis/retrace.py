"""FX2xx — retrace-storm: patterns that retrigger XLA compilation.

A jitted step on a serving hot path must compile a BOUNDED number of
times (the engine's contract: 1 + #buckets + #draft-widths per
session). These rules flag the ways that contract silently breaks:

* **FX201** — a ``jax.jit(...)`` wrapper constructed inside a
  ``for``/``while`` body: every loop iteration builds a fresh wrapper
  with an empty trace cache, so every iteration recompiles.
* **FX202** — ``jax.jit(f)(args)``: the wrapper is built and discarded
  per call; same storm, one expression.
* **FX203** — a tracked jitted callable invoked with a
  shape-polymorphic argument (a slice bounded by a runtime value,
  e.g. ``fn(x[:n])``): each distinct ``n`` is a new shape signature
  and a new compile — per-request lengths must be padded/bucketed
  before dispatch instead.
* **FX204** — a tracked jitted callable with ``static_argnums``
  receiving a computed expression at a static position: every
  distinct value is a new cache entry, so per-request/per-iteration
  values there recompile per step (and unhashable values raise).

"Tracked" means bound from ``jax.jit(...)`` in the same module
(``self._step = jax.jit(...)`` / ``step = jax.jit(...)``).
"""

from __future__ import annotations

import ast
from typing import Dict, List

from flexflow_tpu.analysis.diagnostics import (
    Diagnostic,
    collect_jitted_names,
    is_jit_call,
    name_chain,
)

RULES = {
    "FX201": "jax.jit wrapper constructed inside a loop body",
    "FX202": "jax.jit wrapper immediately invoked (built per call)",
    "FX203": "shape-polymorphic argument to a jitted callable",
    "FX204": "computed value in a static_argnums position",
}


def _has_dynamic_slice(expr: ast.AST) -> bool:
    """A Subscript slice with a runtime-valued bound (``x[:n]``,
    ``x[: len(p)]``) — the shape depends on a per-call Python value.
    Literals, unary-negated literals, and ALL_CAPS names (the module-
    constant convention) are static."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Subscript):
            continue
        slices = (
            node.slice.elts
            if isinstance(node.slice, ast.Tuple)
            else [node.slice]
        )
        for s in slices:
            if not isinstance(s, ast.Slice):
                continue
            for bound in (s.lower, s.upper):
                if bound is None:
                    continue
                if isinstance(bound, (ast.Constant, ast.UnaryOp)):
                    continue
                if isinstance(bound, ast.Name) and bound.id.isupper():
                    continue
                return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, jitted: Dict[str, tuple]):
        self.path = path
        self.jitted = jitted
        self.loop_depth = 0
        self.diags: List[Diagnostic] = []

    def _loop(self, node: ast.AST) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def visit_Call(self, node: ast.Call) -> None:
        if is_jit_call(node) and self.loop_depth > 0:
            self.diags.append(
                Diagnostic(
                    "FX201",
                    self.path,
                    node.lineno,
                    "jax.jit wrapper constructed inside a loop — every "
                    "iteration recompiles; hoist the wrapper out and "
                    "reuse it",
                )
            )
        if isinstance(node.func, ast.Call) and is_jit_call(node.func):
            self.diags.append(
                Diagnostic(
                    "FX202",
                    self.path,
                    node.lineno,
                    "jax.jit(...)(...) builds and discards the wrapper "
                    "per call — cache the jitted callable instead",
                )
            )
        chain = name_chain(node.func)
        if chain is not None and chain[-1] in self.jitted:
            static = self.jitted[chain[-1]]
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Starred):
                    continue
                if _has_dynamic_slice(arg):
                    self.diags.append(
                        Diagnostic(
                            "FX203",
                            self.path,
                            arg.lineno,
                            f"shape-polymorphic argument to jitted "
                            f"'{chain[-1]}' (slice bounded by a runtime "
                            "value) — each distinct length recompiles; "
                            "pad to a bucketed static shape",
                        )
                    )
                if i in static and not isinstance(
                    arg, (ast.Constant, ast.Name, ast.Attribute)
                ):
                    self.diags.append(
                        Diagnostic(
                            "FX204",
                            self.path,
                            arg.lineno,
                            f"computed expression at static position "
                            f"{i} of jitted '{chain[-1]}' — every "
                            "distinct value is a fresh compile",
                        )
                    )
        self.generic_visit(node)


def run(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for path, tree in trees.items():
        v = _Visitor(path, collect_jitted_names(tree))
        v.visit(tree)
        diags.extend(v.diags)
    return diags
