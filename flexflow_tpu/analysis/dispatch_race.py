"""FX1xx — dispatch-race: mutable host state into the async jit queue.

The PR 3 bug class. ``jnp.asarray(x)`` does NOT read ``x``'s buffer at
call time: the read is deferred behind JAX's async dispatch queue. If
``x`` is live scheduler/allocator state (``cache.lengths``, paged
block tables) that the host mutates between iterations, the deferred
read races the mutation and the jitted step silently consumes a future
iteration's state — wrong-context decodes under load, unreproducible
off-peak.

The blessed idiom is ONE of:

* ``serving.engine.snapshot(attr)`` — the repo-wide snapshot helper;
* an explicit ``attr.copy()`` / ``np.array(attr)`` inside the
  ``jnp.asarray`` call.

Rules (attribute-name granularity — ``ast`` cannot resolve types, so a
mutated attribute NAME taints every load of that name; accepted
findings go to the baseline):

* **FX101** — ``jnp.asarray(...)`` whose argument contains a load of an
  attribute that is subscript-mutated somewhere in the scanned file set
  (``obj.attr[i] = ...`` / ``obj.attr[i] += ...``), with no snapshot
  wrapper between the asarray and the load.
* **FX102** — the same un-snapshotted attribute passed directly to a
  callable that was bound from ``jax.jit(...)`` (the array would be
  committed to the queue by the call itself).
* **FX103** — reconcile-phase code (a function taking an
  ``InflightStep`` — by annotation, or a parameter named ``step``/
  ``inflight``) loading a mutated attribute through a ``cache`` object
  instead of the step record. The async double-buffered engine commits
  a step's results one iteration after its dispatch; by reconcile time
  ``cache.lengths`` / ``cache.block_tables`` describe the NEXT step,
  so acceptance/rollback/emit decisions made against them are wrong
  exactly when the pipeline is full — the reconcile must read the
  ``InflightStep`` snapshot (``step.lengths``, ``step.active``,
  ``step.participants``) and nothing else. The same rule covers the
  tree-verify plan (``tree_parents`` / ``tree_plan``): the parent
  table and per-slot ``DraftTree`` travel WITH the step, so a
  scheduler-side mirror describes the NEXT iteration's trees and the
  accept walk would score this step's logits against a different
  topology.
* **FX104** — a search-trace recording call (a ``candidate``/
  ``header``/``event``/``result``/``phase`` method on an object whose
  access path names ``trace``) whose argument loads a mutated
  attribute without a copy. Trace rows are a HISTORY: the searcher
  keeps mutating its view maps / graph tables after the record is
  taken, so a captured live reference lets rows rewrite themselves
  retroactively — the exported artifact then describes a search that
  never happened. Same deferred-read shape as FX101, different queue
  (the JSONL writer instead of the jit dispatch). Pass scalars or a
  fresh ``dict(...)``/``list(...)``/``.copy()``.
* **FX105** — reconcile-phase code loading chunked-prefill progress
  state (``prefill_seq`` / ``prefill_pos`` / ``prefill_dispatched``)
  from anywhere but the step record. A chunk step's cursor travels
  WITH the step (``step.chunks[slot] = (start, size, final)``): the
  dispatcher advances the live ``prefill_dispatched`` cursor the
  moment the NEXT chunk leaves, so by reconcile time the request
  attrs describe a later dispatch — final-chunk / emit decisions made
  against them double-emit or drop the prompt's sampled token. Stores
  are the commit itself (``req.prefill_pos = start + size``) and stay
  sanctioned; loads must come through the step parameter.
* **FX106** — refcount-mutation discipline for the prefix-sharing
  allocator. With hashed prefix pages, a page's refcount is re-derived
  from every live block table (``check_invariants``), so ANY code that
  writes a ``block_tables`` entry or pushes/pops the ``_free_pages``
  heap outside the blessed allocator helpers desynchronizes refcounts
  from ownership — a shared page freed behind its sharers' backs, or a
  leaked page the conservation gauge flags forever. The blessed
  helpers (``_install_page``/``_incref``/``_decref_page``/
  ``_cow_page``/``alloc``/``alloc_shared``/``ensure_position``/
  ``truncate``/``free``/... — see ``_REFCOUNT_BLESSED``) are the ONLY
  functions allowed to touch either structure; everything else must
  route through them.
* **FX107** — swap/eviction ledger discipline for the
  pressure-degradation allocator. The host-swap table (``_swapped``:
  handle -> staged pages + bytes), the publication-only LRU
  (``_pub_only``: page -> (stamp, wait window)), and the downed host
  set (``_hosts_down``) are each audited by ``check_invariants`` —
  the swap-bytes budget, the page conservation sum, and admission
  routing all re-derive from them. A raw mutation (subscript store,
  ``del``, rebinding, or a mutating method call like ``.pop()``/
  ``.clear()``/``.add()``) outside the blessed helpers
  (``swap_out``/``swap_in``/``discard_swap``/``_incref``/
  ``_decref_page``/``_evict_prefix_page``/``mark_host_down``/
  ``mark_host_up`` — see ``_SWAP_BLESSED``) double-frees staged
  bytes, resurrects evicted pages, or routes admissions to a dead
  host. Same blessed-set machinery as FX106, different ledgers.
* **FX108** — cross-engine swap-handle lifetime (the prefill→decode
  handoff). A handle/record produced by a staging call (``swap_out``/
  ``export_swap``/``stage_out``) is a MOVE token: ``export_swap`` pops
  the source ledger entry and ``import_swap`` installs it under a
  fresh handle, so the original is dead the moment it is consumed.
  Two findings: (1) one function consumes the same staged
  handle/record variable twice (``swap_in``/``import_swap``/
  ``export_swap``/``discard_swap``) — the second consumption restores
  pages the first already owns (a KeyError at best, two engines
  decoding one stream's KV at worst); (2) handoff-phase code (a
  function with a ``src``/``source``/``src_cache``/``source_cache``/
  ``src_engine``/``source_engine`` parameter) loads live pool/table
  state (``k``/``v``/``k_scale``/``v_scale``/``block_tables``/
  ``lengths``/``_swapped``) through that parameter without a staging
  copy — the source engine keeps serving while the handoff reads, so
  a live reference ships rows the next decode step is rewriting; the
  staged record (``export_swap``'s host-side numpy copies) is the
  only sanctioned carrier across the engine boundary.
* **FX109** — device-resident multi-step decode discipline (the fused
  K-step ``lax.scan`` window). Two findings: (a) a multi-step dispatch
  function (``multi`` + ``dispatch`` in the name) captures live
  mutated host allocator state (``lengths`` / ``block_tables`` /
  ``_free_pages``) without a snapshot — the scan executes K decode
  steps behind the async dispatch queue, so a live reference is up to
  K iterations stale when the device finally reads it, K times the
  exposure of the single-step FX101 race. Scalars materialized at
  call time (``int()``/``len()``/``min()``...) are synchronous host
  reads and stay sanctioned, as do Assign/AugAssign store TARGETS
  (the dispatch-side pre-advance ``cache.lengths[act] += limits`` is
  the commit itself, not a capture). (b) reconcile-phase code reads
  multi-step window state (``k_steps`` / ``step_limits`` /
  ``device_tokens`` / ``device_mask`` / ``device_lengths``) from
  anywhere but the step record — the window's geometry travels WITH
  its ``InflightStep``; any scheduler-side mirror is a whole window
  stale under async double-buffering, so commit/rollback decisions
  made against it truncate to the wrong length or emit phantom
  steps. Part (a) also applies to tree-verify dispatch functions
  (``tree`` + ``dispatch`` in the name): the parent table and page
  claims ride the same async queue, so live allocator state handed
  to the jitted tree step (or stored on the ``InflightStep``) must
  cross as a snapshot.
* **FX110** — adapter-pool ledger discipline for the multi-tenant
  LoRA pool (``serving/tenancy/adapters.AdapterPool``), FX106's rule
  applied to its sibling allocator: a subscript store into an
  ``adapter_tables`` / ``slot_adapter`` / ``_adapter_refcounts``
  attribute, or a ``heapq`` push/pop reaching the
  ``_free_adapter_pages`` heap, outside the blessed pool helpers
  (``load``/``unload``/``attach``/``detach`` and the page-install/
  free seams — see ``_ADAPTER_BLESSED``). The pool's refcounts are
  1 (loaded) + 1 per attached slot and ``check_invariants``
  re-derives them from the tables, so a raw write frees an
  adapter's pages under a slot mid-decode (the gather then reads a
  recycled page: silent weight corruption, the tenant-isolation
  bug) or leaks them forever. The ledger names are disjoint from
  FX106's on purpose — the two allocators can be linted in one pass
  without cross-talk.

* **FX111** — journal-before-publish discipline for the durable
  serving journal (``serving/journal.RequestJournal``): a mutation of
  a request's ``generated`` token list (``.append``/``.extend``/
  ``.insert`` call, subscript store/delete, or rebinding the
  attribute) outside the blessed emit seam (``_emit`` — see
  ``_EMIT_BLESSED``). ``_emit`` is the single point where a token
  becomes stream-visible AND journal-noted (``journal.note``) in the
  same breath; ``_end_iteration`` then flushes the noted run as a
  commit record before the front door can publish it. A raw
  ``req.generated.append(...)`` anywhere else produces a token the
  journal never saw, so a crash-restart replays the journal and
  resumes one token short — the recovered stream silently diverges
  from what the client already received, breaking token-identical
  resume. ``__init__`` is construction, not emission (same rationale
  as FX106), and recovery code seeds ``generated`` via the Request
  constructor for exactly that reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from flexflow_tpu.analysis.diagnostics import (
    Diagnostic,
    collect_jitted_names,
    name_chain,
)

RULES = {
    "FX101": "mutable host attribute into jnp.asarray without a snapshot",
    "FX102": "mutable host attribute passed raw into a jitted callable",
    "FX103": "reconcile reads live cache state instead of the "
    "InflightStep snapshot",
    "FX104": "search-trace hook captures live mutable state without a "
    "copy",
    "FX105": "reconcile reads live chunk-progress attrs instead of the "
    "InflightStep chunk record",
    "FX106": "block-table write or free-heap mutation outside the "
    "blessed refcount helpers",
    "FX107": "swap/eviction ledger mutation outside the blessed "
    "allocator helpers",
    "FX108": "cross-engine swap handle consumed twice, or handoff code "
    "reading live source-engine pool state",
    "FX109": "multi-step or tree-verify dispatch captures live host "
    "state, or reconcile reads window state off the step record",
    "FX110": "adapter-pool table/refcount write or free-heap mutation "
    "outside the blessed AdapterPool helpers",
    "FX111": "stream-visible token commit (a 'generated' list "
    "mutation) outside the blessed journal-noting emit seam",
}

#: the only functions allowed to write `block_tables` entries or touch
#: the `_free_pages` heap (FX106) — the allocator's refcount seams plus
#: the fault injector's sanctioned steal/restore pair. `__init__` is
#: construction, not mutation (same rationale as collect_mutated_attrs).
_REFCOUNT_BLESSED = {
    "__init__",
    "alloc",
    "alloc_shared",
    "ensure_position",
    "truncate",
    "free",
    "claim",
    "end_inflight",
    "_release_page",
    "_decref_entry",
    "_decref_page",
    "_incref",
    "_cow_page",
    "_install_page",
    "register_prefix",
    "_page_faults",
    "release_stolen_pages",
    # PR 14 pressure-degradation seams: eviction reroutes a retained
    # page back to the heap, _pop_free_page is the evict-or-pop gate
    # every allocation path drains, swap_in reinstalls staged pages
    "_evict_prefix_page",
    "_pop_free_page",
    "swap_in",
}

#: the only functions allowed to mutate the swap/eviction ledgers
#: (FX107): the host-swap table `_swapped`, the publication-only LRU
#: `_pub_only`, and the downed-host set `_hosts_down`. `__init__` is
#: construction, not mutation (same rationale as FX106).
_SWAP_BLESSED = {
    "__init__",
    "swap_out",
    "swap_in",
    "discard_swap",
    "_incref",
    "_decref_page",
    "_evict_prefix_page",
    "mark_host_down",
    "mark_host_up",
    # cross-engine handoff seams (FX108's domain): export pops the
    # local ledger entry, import installs under a fresh local handle
    "export_swap",
    "import_swap",
}

_SWAP_LEDGER_ATTRS = {"_swapped", "_pub_only", "_hosts_down"}

#: the only functions allowed to write the multi-LoRA pool's ledgers
#: (FX110): the load/unload/attach/detach surface the scheduler calls
#: plus the page-install/free seams they delegate to. `__init__` is
#: construction, not mutation (same rationale as FX106).
_ADAPTER_BLESSED = {
    "__init__",
    "load",
    "unload",
    "attach",
    "detach",
    "_install_adapter_page",
    "_free_adapter_page",
    "_pop_free_adapter_page",
}

#: AdapterPool's refcount-bearing ledgers — deliberately disjoint from
#: FX106's block_tables/_free_pages names so both allocators lint in
#: one pass without cross-talk
_ADAPTER_LEDGER_ATTRS = {
    "adapter_tables",
    "slot_adapter",
    "_adapter_refcounts",
}

#: the only functions allowed to mutate a request's `generated` token
#: list (FX111): `_emit` pairs the append with `journal.note` so every
#: stream-visible token is journal-noted before the front door can
#: publish it. `__init__` is construction, not emission (same
#: rationale as FX106) — recovery seeds `generated` through the
#: Request constructor.
_EMIT_BLESSED = {
    "__init__",
    "_emit",
}

#: list-method calls that grow or rewrite the `generated` token run
_GENERATED_MUTATORS = {"append", "extend", "insert"}

#: method calls that mutate a dict/set ledger in place
_SWAP_MUTATING_METHODS = {
    "pop",
    "popitem",
    "update",
    "clear",
    "setdefault",
    "add",
    "discard",
    "remove",
}

_STEP_PARAM_NAMES = {"step", "inflight"}

#: calls that PRODUCE a staged cross-engine token (handle or record):
#: the variable they bind is a move token, live until first consumption
_HANDOFF_STAGING_CALLS = {"swap_out", "export_swap", "stage_out"}

#: calls that CONSUME a staged token — each kills its argument
#: (export pops the ledger entry; import/swap_in install it; discard
#: returns the budget). A second consumption is the FX108 bug class.
_HANDOFF_CONSUMING_CALLS = {
    "swap_in",
    "import_swap",
    "export_swap",
    "discard_swap",
}

#: parameter names marking a function as handoff-phase code holding a
#: reference to the SOURCE engine/cache of a KV movement
_HANDOFF_SRC_PARAMS = {
    "src",
    "source",
    "src_cache",
    "source_cache",
    "src_engine",
    "source_engine",
}

#: live pool/table state on an engine's cache that must never cross
#: the engine boundary by reference — the staged record is the carrier
_HANDOFF_POOL_ATTRS = {
    "k",
    "v",
    "k_scale",
    "v_scale",
    "block_tables",
    "lengths",
    "_swapped",
}

#: chunked-prefill cursor state on Request — the live view a chunk
#: reconcile must never read (FX105); the snapshot is `step.chunks`
_CHUNK_PROGRESS_ATTRS = {"prefill_seq", "prefill_pos", "prefill_dispatched"}

#: host allocator state a multi-step dispatch must snapshot before the
#: fused scan captures it (FX109a). Deliberately NOT the full mutated
#: set: the device pools (`cache.k`/`cache.v`) are donated device
#: arrays that legitimately ride into the jit raw.
_MULTISTEP_HOST_ATTRS = {
    "lengths",
    "block_tables",
    "_free_pages",
    "_free_pages_h",
}

#: single-name builtins whose call materializes a host SCALAR at call
#: time — a synchronous read, immune to the deferred-read race, so a
#: multi-step dispatch may apply them to live state (`int(lengths[s])`)
_MULTI_DISPATCH_SCALARS = {"int", "float", "bool", "len", "min", "max"}

#: fused-window state on InflightStep — reconcile-phase code must read
#: these through the step record, never a scheduler-side mirror (FX109b)
_WINDOW_STATE_ATTRS = {
    "k_steps",
    "step_limits",
    "device_tokens",
    "device_mask",
    "device_lengths",
}

#: tree-verify plan state on InflightStep — the dispatched parent table
#: and the per-slot DraftTree plan; the reconcile's accept walk must
#: read these through the step record, never a scheduler-side mirror
#: (FX103's tree extension)
_TREE_PLAN_ATTRS = {"tree_parents", "tree_plan"}

_ASARRAY_CHAINS = {("jnp", "asarray"), ("jax", "numpy", "asarray")}
_SNAPSHOT_NAMES = {"snapshot"}
# builtins that materialize a fresh container — a copy by construction
_COPYING_BUILTINS = {"dict", "list", "tuple", "sorted", "set", "frozenset"}

#: SearchTrace recording surface (telemetry/search_trace.py); `phase`
#: is included for its kwargs
_TRACE_METHODS = {"candidate", "header", "event", "result", "phase"}


def _is_asarray(func: ast.AST) -> bool:
    return name_chain(func) in _ASARRAY_CHAINS


def _is_snapshot_call(node: ast.Call) -> bool:
    """A call that yields an immutable copy: ``x.copy()``,
    ``np.array(x)`` (copies by default), a fresh-container builtin
    (``dict(x)``/``list(x)``/...), or the blessed ``snapshot(x)``
    helper."""
    if isinstance(node.func, ast.Attribute) and node.func.attr == "copy":
        return True
    chain = name_chain(node.func)
    if chain is None:
        return False
    if chain[-1] in _SNAPSHOT_NAMES:
        return True
    if len(chain) == 1 and chain[0] in _COPYING_BUILTINS:
        return True
    return len(chain) >= 2 and chain[-2] in ("np", "numpy") and (
        chain[-1] == "array"
    )


def collect_mutated_attrs(trees: Dict[str, ast.Module]) -> Set[str]:
    """Attribute names that are subscript-assigned anywhere in the file
    set — the in-place array writes a deferred host read can race.
    Writes inside ``__init__`` don't count: construction precedes
    sharing, so init-time population (e.g. a cache's per-layer device
    dicts) cannot race a dispatch."""
    mutated: Set[str] = set()

    def record(target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                record(el)
        elif isinstance(target, ast.Subscript) and isinstance(
            target.value, ast.Attribute
        ):
            mutated.add(target.value.attr)

    def visit(node: ast.AST) -> None:
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "__init__"
        ):
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                record(t)
        elif isinstance(node, ast.AugAssign):
            record(node.target)
        for child in ast.iter_child_nodes(node):
            visit(child)

    for tree in trees.values():
        visit(tree)
    return mutated


def _tainted_loads(
    expr: ast.AST, mutated: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for every load of a mutated attribute inside `expr`
    that is not protected by a snapshot wrapper."""
    found: List[Tuple[str, int]] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Call) and _is_snapshot_call(node):
            return  # everything below this call is snapshotted
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in mutated
        ):
            found.append((node.attr, node.lineno))
            return  # the inner chain is the same access path
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return found


def _annotation_names(node: ast.AST) -> Set[str]:
    """Every dotted/string name appearing in an annotation expression
    (handles Optional["InflightStep"], engine.InflightStep, etc.)."""
    names: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.add(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            names.add(sub.value.rsplit(".", 1)[-1])
    return names


def _step_params(fn) -> Set[str]:
    """Parameter names of `fn` that carry an InflightStep — annotated
    as one, or conventionally named step/inflight. Non-empty marks the
    function as reconcile-phase code — EXCEPT dispatch-side functions
    ('dispatch' in the name): they take the snapshot, so they read live
    state by definition (e.g. decode_dispatch's `chain` step is a
    device-token source, not a commit target)."""
    if "dispatch" in fn.name:
        return set()
    params: Set[str] = set()
    args = list(fn.args.posonlyargs) + list(fn.args.args) + list(
        fn.args.kwonlyargs
    )
    for a in args:
        if a.arg in _STEP_PARAM_NAMES:
            params.add(a.arg)
        elif a.annotation is not None and (
            "InflightStep" in _annotation_names(a.annotation)
        ):
            params.add(a.arg)
    return params


def _reconcile_violations(
    fn, mutated: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for loads of a mutated attribute reached through a
    `cache` object inside a reconcile-phase function — live allocator/
    length state the snapshot on the step record exists to replace.
    Loads through the step parameter (step.lengths) and non-cache state
    (self.running, self.stats) are the sanctioned paths."""
    found: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in mutated
        ):
            continue
        chain = name_chain(node)
        if chain is not None and "cache" in chain[:-1]:
            found.append((node.attr, node.lineno))
    return found


def _chunk_progress_violations(
    fn, step_params: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for loads of chunked-prefill cursor state inside a
    reconcile-phase function that do not come through the step
    parameter. Stores (the commit: ``req.prefill_pos = start + size``)
    are the sanctioned write-back and never match; the sanctioned read
    is the step's own record (``step.chunks[slot]``)."""
    found: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in _CHUNK_PROGRESS_ATTRS
        ):
            continue
        chain = name_chain(node)
        if chain is not None and chain[0] in step_params:
            continue
        found.append((node.attr, node.lineno))
    return found


def _is_multistep_dispatch(fn) -> bool:
    """Multi-step dispatch code by the same name convention _step_params
    uses to EXEMPT dispatch functions from FX103/FX105: it takes the
    window's snapshots, so it reads live state by definition — but what
    it hands the fused scan must be snapshotted (FX109a)."""
    return "multi" in fn.name and "dispatch" in fn.name


def _is_tree_dispatch(fn) -> bool:
    """Tree-verify dispatch code, by the same naming convention as
    _is_multistep_dispatch ('tree' + 'dispatch'). Exempt from
    FX103/FX105 like every dispatch function — it takes the snapshots
    — but what it hands the jitted tree step or stores on the
    InflightStep must be snapshotted (FX109): the parent table is read
    behind the async dispatch queue and walked again at reconcile, an
    iteration after the live tables have moved on."""
    return "tree" in fn.name and "dispatch" in fn.name


def _multistep_capture_violations(
    fn, mutated: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for loads of live host allocator state inside a
    multi-step dispatch function with no snapshot wrapper and no
    scalar materialization. The fused scan reads its captures behind
    the async dispatch queue — K steps after this function returns —
    so every mutable host array must cross as a copy. Store targets
    (the pre-advance ``cache.lengths[act] += limits``) are the
    dispatch-side commit and never match."""
    attrs = _MULTISTEP_HOST_ATTRS & mutated
    found: List[Tuple[str, int]] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            if _is_snapshot_call(node):
                return  # copied below here — that IS the snapshot
            chain = name_chain(node.func)
            if (
                chain is not None
                and len(chain) == 1
                and chain[0] in _MULTI_DISPATCH_SCALARS
            ):
                return  # scalar materialized at call time: synchronous
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            # store targets are the dispatch-side commit (pre-advance);
            # only the VALUE can leak a live reference
            visit(node.value)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in attrs
        ):
            chain = name_chain(node)
            if chain is not None and "cache" in chain[:-1]:
                found.append((node.attr, node.lineno))
                return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    return found


def _window_state_violations(
    fn, step_params: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for loads of fused-window state inside a
    reconcile-phase function that do not come through the step
    parameter. The window's geometry (k_steps, per-slot limits) and
    per-step device stacks travel WITH the InflightStep; a
    scheduler-side mirror is one whole window stale under async
    double-buffering."""
    found: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in _WINDOW_STATE_ATTRS
        ):
            continue
        chain = name_chain(node)
        if chain is not None and chain[0] in step_params:
            continue
        found.append((node.attr, node.lineno))
    return found


def _tree_plan_violations(
    fn, step_params: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for loads of tree-verify plan state
    (``tree_parents`` / ``tree_plan``) inside a reconcile-phase
    function that do not come through the step parameter. The parent
    table and the per-slot DraftTree plan travel WITH the
    InflightStep; under async double-buffering a scheduler-side mirror
    describes the NEXT iteration's trees, so an accept walk against it
    scores this step's logits on a different topology — wrong branch
    accepted, wrong rows compacted."""
    found: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in _TREE_PLAN_ATTRS
        ):
            continue
        chain = name_chain(node)
        if chain is not None and chain[0] in step_params:
            continue
        found.append((node.attr, node.lineno))
    return found


def _refcount_violations(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """(description, line, offender) for refcount-bearing mutations
    outside the blessed allocator helpers: a subscript store into a
    ``block_tables`` attribute, or a ``heapq.heappush``/``heappop``
    whose argument reaches a ``_free_pages`` attribute (or a
    ``_free_pages_h`` per-host heap — the pod-serving partition of the
    same pool). Module-level code reports under the pseudo-name
    '<module>'."""
    found: List[Tuple[str, int, str]] = []

    def is_bt_store(node: ast.AST) -> bool:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            elif isinstance(t, ast.Subscript) and isinstance(
                t.value, ast.Attribute
            ) and t.value.attr == "block_tables":
                return True
        return False

    def heap_op_attr(node: ast.AST) -> Optional[str]:
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("heappush", "heappop")
        ):
            return None
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Attribute) and (
                    sub.attr in ("_free_pages", "_free_pages_h")
                ):
                    return sub.attr
        return None

    def visit(node: ast.AST, owner: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            owner = node.name
            if owner in _REFCOUNT_BLESSED:
                return
        if is_bt_store(node):
            found.append(
                ("writes a 'block_tables' entry", node.lineno, owner)
            )
        else:
            heap = heap_op_attr(node)
            if heap is not None:
                found.append(
                    (f"mutates the '{heap}' heap", node.lineno, owner)
                )
        for child in ast.iter_child_nodes(node):
            visit(child, owner)

    visit(tree, "<module>")
    return found


def _adapter_violations(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """(description, line, offender) for adapter-pool ledger mutations
    outside the blessed AdapterPool helpers (FX110): a subscript store
    (or AugAssign) into an ``adapter_tables`` / ``slot_adapter`` /
    ``_adapter_refcounts`` attribute, or a ``heapq.heappush``/
    ``heappop`` whose argument reaches the ``_free_adapter_pages``
    heap. Reads never match — ``slot_tables``/``row_tables`` gather
    from the ledgers freely, and ``check_invariants`` audits them.
    Module-level code reports under the pseudo-name '<module>'."""
    found: List[Tuple[str, int, str]] = []

    def ledger_store_attr(node: ast.AST) -> Optional[str]:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            elif isinstance(t, ast.Subscript) and isinstance(
                t.value, ast.Attribute
            ) and t.value.attr in _ADAPTER_LEDGER_ATTRS:
                return t.value.attr
        return None

    def heap_reached(node: ast.AST) -> bool:
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("heappush", "heappop")
        ):
            return False
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Attribute) and (
                    sub.attr == "_free_adapter_pages"
                ):
                    return True
        return False

    def visit(node: ast.AST, owner: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            owner = node.name
            if owner in _ADAPTER_BLESSED:
                return
        attr = ledger_store_attr(node)
        if attr is not None:
            found.append(
                (f"writes the '{attr}' ledger", node.lineno, owner)
            )
        elif heap_reached(node):
            found.append(
                ("mutates the '_free_adapter_pages' heap", node.lineno,
                 owner)
            )
        for child in ast.iter_child_nodes(node):
            visit(child, owner)

    visit(tree, "<module>")
    return found


def _journal_violations(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """(description, line, offender) for stream-visible token commits
    outside the blessed emit seam (FX111): an ``.append``/``.extend``/
    ``.insert`` call on a ``generated`` attribute, a subscript store or
    ``del`` into one, or rebinding the attribute itself, anywhere but
    ``_emit`` (see ``_EMIT_BLESSED``). Reads never match — the
    scheduler's length checks, the front door's publish cursor, and the
    journal's submit snapshot all read ``generated`` freely. Module-
    level code reports under the pseudo-name '<module>'."""
    found: List[Tuple[str, int, str]] = []

    def is_generated_attr(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr == "generated"

    def mutation_of(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _GENERATED_MUTATORS
            and is_generated_attr(node.func.value)
        ):
            return f"calls .{node.func.attr}() on a 'generated' list"
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            elif isinstance(t, ast.Subscript) and is_generated_attr(
                t.value
            ):
                return "stores into a 'generated' list slot"
            elif is_generated_attr(t):
                return "rebinds a 'generated' attribute"
        return None

    def visit(node: ast.AST, owner: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            owner = node.name
            if owner in _EMIT_BLESSED:
                return
        what = mutation_of(node)
        if what is not None:
            found.append((what, node.lineno, owner))
        for child in ast.iter_child_nodes(node):
            visit(child, owner)

    visit(tree, "<module>")
    return found


def _swap_violations(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """(description, line, offender) for swap/eviction ledger mutations
    outside the blessed allocator helpers (FX107): subscript stores,
    ``del`` statements, attribute rebinding, or in-place mutating
    method calls reaching ``_swapped`` / ``_pub_only`` /
    ``_hosts_down``. Reads never match — resurrection checks, budget
    math, and the invariant audit all read freely."""
    found: List[Tuple[str, int, str]] = []

    def ledger_attr_of(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and (
            node.attr in _SWAP_LEDGER_ATTRS
        ):
            return node.attr
        return None

    def store_target_attr(t: ast.AST) -> Optional[str]:
        # `x._swapped[h] = ...` / `x._swapped = {}` / `del x._pub_only[p]`
        if isinstance(t, ast.Subscript):
            return ledger_attr_of(t.value)
        return ledger_attr_of(t)

    def visit(node: ast.AST, owner: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            owner = node.name
            if owner in _SWAP_BLESSED:
                return
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign,)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
                continue
            attr = store_target_attr(t)
            if attr is not None:
                found.append(
                    (f"writes the '{attr}' ledger", node.lineno, owner)
                )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _SWAP_MUTATING_METHODS
        ):
            attr = ledger_attr_of(node.func.value)
            if attr is not None:
                found.append(
                    (
                        f"mutates the '{attr}' ledger via "
                        f".{node.func.attr}()",
                        node.lineno,
                        owner,
                    )
                )
        for child in ast.iter_child_nodes(node):
            visit(child, owner)

    visit(tree, "<module>")
    return found


def _handle_reuse_violations(fn) -> List[Tuple[str, str, int]]:
    """(variable, consumer, line) for every consumption of a staged
    handle/record variable AFTER its first — the double-restore shape
    of FX108. Name-granular within one function: a variable bound from
    a staging call (``h = cache.swap_out(slot)``, ``rec =
    cache.export_swap(h)``) is a move token; each consuming call
    taking it as an argument kills it, and a later consumption (or one
    inside a loop body, which re-runs) is reported. Rebinding from a
    fresh staging call revives the name (a loop-carried
    ``handle = stage(...)`` per iteration is the sanctioned idiom)."""
    found: List[Tuple[str, str, int]] = []
    consumed: Dict[str, int] = {}  # var -> line of first consumption
    staged: Dict[str, int] = {}  # var -> loop depth at staging

    def call_method(node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Attribute):
            return node.func.attr
        if isinstance(node.func, ast.Name):
            return node.func.id
        return None

    loop_depth = 0

    def visit(node: ast.AST) -> None:
        nonlocal loop_depth
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            method = call_method(node.value)
            if method in _HANDOFF_STAGING_CALLS:
                visit(node.value)  # args may consume earlier tokens
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        staged[t.id] = loop_depth
                        consumed.pop(t.id, None)
                return
        if isinstance(node, ast.Call):
            method = call_method(node)
            if method in _HANDOFF_CONSUMING_CALLS:
                for arg in node.args:
                    if not (
                        isinstance(arg, ast.Name) and arg.id in staged
                    ):
                        continue
                    # a token staged OUTSIDE a loop but consumed inside
                    # one is consumed on every iteration — same bug as
                    # two sequential consumptions
                    if arg.id in consumed or loop_depth > staged[arg.id]:
                        found.append((arg.id, method, node.lineno))
                    consumed.setdefault(arg.id, node.lineno)
        in_loop = isinstance(node, (ast.For, ast.While, ast.AsyncFor))
        if in_loop:
            loop_depth += 1
        for child in ast.iter_child_nodes(node):
            visit(child)
        if in_loop:
            loop_depth -= 1

    for stmt in fn.body:
        visit(stmt)
    return found


def _src_params(fn) -> Set[str]:
    """Parameter names of `fn` that carry the SOURCE engine/cache of a
    handoff — by convention (src/source/src_cache/...), the same
    name-granular marking _step_params uses for reconcile code."""
    params: Set[str] = set()
    args = list(fn.args.posonlyargs) + list(fn.args.args) + list(
        fn.args.kwonlyargs
    )
    for a in args:
        if a.arg in _HANDOFF_SRC_PARAMS:
            params.add(a.arg)
    return params


def _live_source_violations(
    fn, src_params: Set[str]
) -> List[Tuple[str, int]]:
    """(attr, line) for loads of live pool/table state reached through
    a source-engine parameter without a staging copy. The copy wrappers
    _is_snapshot_call blesses (``np.array``/``.copy()``/``snapshot``)
    sanction the load — they ARE the staging — as do the staging calls
    themselves (``source.export_swap(...)`` reads `_swapped` by
    design, through a blessed method)."""
    found: List[Tuple[str, int]] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            if _is_snapshot_call(node):
                return  # copied below here: that IS the staging
            method = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else None
            )
            if method in _HANDOFF_STAGING_CALLS or (
                method in _HANDOFF_CONSUMING_CALLS
            ):
                return  # the blessed movement seams
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in _HANDOFF_POOL_ATTRS
        ):
            chain = name_chain(node)
            if chain is not None and chain[0] in src_params:
                found.append((node.attr, node.lineno))
                return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    return found


def _is_trace_hook(node: ast.Call) -> bool:
    """A SearchTrace recording call: `<...>.trace.candidate(...)`,
    `trace.result(...)`, `self._trace.event(...)` — the method is one
    of the recording surface and the object path names a trace.
    `tracer` objects (telemetry/trace.py, a different API) don't
    match."""
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in _TRACE_METHODS:
        return False
    chain = name_chain(node.func)
    if chain is None or len(chain) < 2:
        return False
    owner = chain[-2]
    return owner in ("trace", "_trace", "search_trace") or (
        owner.endswith("_trace")
    )


def run(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    mutated = collect_mutated_attrs(trees)
    diags: List[Diagnostic] = []
    for path, tree in trees.items():
        for node in ast.walk(tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if _is_multistep_dispatch(node):
                for attr, line in _multistep_capture_violations(
                    node, mutated
                ):
                    diags.append(
                        Diagnostic(
                            "FX109",
                            path,
                            line,
                            f"multi-step dispatch '{node.name}' captures "
                            f"live host attribute '{attr}' into the "
                            "fused K-step window without a snapshot — "
                            "the scan reads it behind the dispatch "
                            "queue, up to K iterations after this call "
                            "returns; wrap it in snapshot()/np.array or "
                            "materialize a scalar (int())",
                        )
                    )
            elif _is_tree_dispatch(node):
                for attr, line in _multistep_capture_violations(
                    node, mutated
                ):
                    diags.append(
                        Diagnostic(
                            "FX109",
                            path,
                            line,
                            f"tree-verify dispatch '{node.name}' "
                            f"captures live host attribute '{attr}' "
                            "into the jitted tree step without a "
                            "snapshot — the parent table and page "
                            "claims ride the async dispatch queue and "
                            "the reconcile walks them an iteration "
                            "later; wrap it in snapshot()/np.array or "
                            "materialize a scalar (int())",
                        )
                    )
            steps = _step_params(node)
            if not steps:
                continue
            for attr, line in _window_state_violations(node, steps):
                diags.append(
                    Diagnostic(
                        "FX109",
                        path,
                        line,
                        f"reconcile-phase function '{node.name}' reads "
                        f"multi-step window state '{attr}' off the "
                        "step record — the window's geometry travels "
                        "WITH its InflightStep; a scheduler-side "
                        "mirror is a whole window stale under async "
                        "double-buffering",
                    )
                )
            for attr, line in _tree_plan_violations(node, steps):
                diags.append(
                    Diagnostic(
                        "FX103",
                        path,
                        line,
                        f"reconcile-phase function '{node.name}' reads "
                        f"tree-verify plan state '{attr}' off the step "
                        "record — the parent table and DraftTree plan "
                        "travel WITH their InflightStep; a scheduler-"
                        "side mirror describes the NEXT iteration's "
                        "trees under async double-buffering, so the "
                        "accept walk scores the wrong topology",
                    )
                )
            for attr, line in _reconcile_violations(node, mutated):
                diags.append(
                    Diagnostic(
                        "FX103",
                        path,
                        line,
                        f"reconcile-phase function '{node.name}' reads "
                        f"live 'cache.{attr}' — between dispatch and "
                        "reconcile that state belongs to the NEXT step; "
                        "read the InflightStep snapshot instead",
                    )
                )
            for attr, line in _chunk_progress_violations(node, steps):
                diags.append(
                    Diagnostic(
                        "FX105",
                        path,
                        line,
                        f"reconcile-phase function '{node.name}' reads "
                        f"live chunk-progress attr '{attr}' — the "
                        "dispatcher advances it for later chunks while "
                        "this step is in flight; read the step's own "
                        "cursor record (step.chunks) instead",
                    )
                )
    for path, tree in trees.items():
        for what, line, owner in _refcount_violations(tree):
            diags.append(
                Diagnostic(
                    "FX106",
                    path,
                    line,
                    f"'{owner}' {what} outside the blessed refcount "
                    "helpers — prefix-shared pages derive their "
                    "refcounts from block tables, so raw mutation "
                    "desynchronizes ownership (shared page freed under "
                    "its sharers, or leaked forever); route through "
                    "alloc/alloc_shared/ensure_position/truncate/free "
                    "or the _incref/_decref seams",
                )
            )
    for path, tree in trees.items():
        for what, line, owner in _swap_violations(tree):
            diags.append(
                Diagnostic(
                    "FX107",
                    path,
                    line,
                    f"'{owner}' {what} outside the blessed swap/"
                    "eviction helpers — check_invariants re-derives "
                    "the swap-bytes budget, page conservation, and "
                    "host routing from these ledgers, so raw mutation "
                    "double-frees staged bytes or resurrects evicted "
                    "pages; route through swap_out/swap_in/"
                    "discard_swap, the _incref/_decref_page seams, or "
                    "mark_host_down/mark_host_up",
                )
            )
    for path, tree in trees.items():
        for what, line, owner in _adapter_violations(tree):
            diags.append(
                Diagnostic(
                    "FX110",
                    path,
                    line,
                    f"'{owner}' {what} outside the blessed AdapterPool "
                    "helpers — adapter-page refcounts are 1 (loaded) "
                    "plus 1 per attached slot, so a raw write frees an "
                    "adapter's pages under a slot mid-decode (the "
                    "gather reads a recycled page: another tenant's "
                    "weights) or leaks them forever; route through "
                    "load/unload/attach/detach or the "
                    "_install_adapter_page/_free_adapter_page seams",
                )
            )
    for path, tree in trees.items():
        for what, line, owner in _journal_violations(tree):
            diags.append(
                Diagnostic(
                    "FX111",
                    path,
                    line,
                    f"'{owner}' {what} outside the blessed emit seam — "
                    "_emit pairs the append with journal.note so every "
                    "stream-visible token is journal-noted before the "
                    "front door publishes it; a raw mutation produces "
                    "a token the journal never saw, so crash-restart "
                    "replay resumes one token short and the recovered "
                    "stream silently diverges from what the client "
                    "already received",
                )
            )
    for path, tree in trees.items():
        for node in ast.walk(tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            for var, consumer, line in _handle_reuse_violations(node):
                diags.append(
                    Diagnostic(
                        "FX108",
                        path,
                        line,
                        f"'{node.name}' consumes staged swap token "
                        f"'{var}' again via '{consumer}' — a staged "
                        "handle/record is a move token (export pops "
                        "the source ledger, import installs it under "
                        "a fresh handle); the second consumption "
                        "restores pages another engine already owns",
                    )
                )
            srcs = _src_params(node)
            if not srcs:
                continue
            for attr, line in _live_source_violations(node, srcs):
                diags.append(
                    Diagnostic(
                        "FX108",
                        path,
                        line,
                        f"handoff-phase function '{node.name}' reads "
                        f"live source-engine state '{attr}' by "
                        "reference — the source keeps serving while "
                        "the handoff reads; stage a copy "
                        "(export_swap's host buffers, .copy(), "
                        "np.array) across the engine boundary instead",
                    )
                )
    for path, tree in trees.items():
        jitted = collect_jitted_names(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_asarray(node.func):
                for arg in node.args:
                    for attr, line in _tainted_loads(arg, mutated):
                        diags.append(
                            Diagnostic(
                                "FX101",
                                path,
                                line,
                                f"mutable host attribute '{attr}' flows "
                                "into jnp.asarray without a snapshot "
                                "(.copy()/np.array/snapshot) — the "
                                "deferred host read races later "
                                "mutation behind the dispatch queue",
                            )
                        )
                continue
            if _is_trace_hook(node):
                args = list(node.args) + [
                    kw.value for kw in node.keywords if kw.arg is not None
                ]
                for arg in args:
                    if isinstance(arg, ast.Starred):
                        continue
                    for attr, line in _tainted_loads(arg, mutated):
                        diags.append(
                            Diagnostic(
                                "FX104",
                                path,
                                line,
                                f"search-trace hook captures mutable "
                                f"attribute '{attr}' without a copy — "
                                "the searcher mutates it after the "
                                "record is taken, so the exported row "
                                "would rewrite itself; pass a scalar "
                                "or dict(...)/list(...)/.copy()",
                            )
                        )
                continue
            chain = name_chain(node.func)
            if chain is not None and chain[-1] in jitted:
                for arg in node.args:
                    if isinstance(arg, ast.Starred):
                        continue
                    for attr, line in _tainted_loads(arg, mutated):
                        diags.append(
                            Diagnostic(
                                "FX102",
                                path,
                                line,
                                f"mutable host attribute '{attr}' passed "
                                f"raw into jitted callable "
                                f"'{chain[-1]}' — snapshot it before "
                                "dispatch",
                            )
                        )
    return diags
