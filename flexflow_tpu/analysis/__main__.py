"""``python -m flexflow_tpu.analysis`` — the fxlint CLI."""

import sys

from flexflow_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
