"""fxlint: static analysis for the repo's own bug classes.

Unity (OSDI'22) validates parallelization decisions BEFORE execution;
this package does the same for the JAX-side invariants this codebase
has already paid to learn:

* **dispatch-race** (FX1xx, `dispatch_race.py`) — the PR 3 bug class:
  a mutable host array (``cache.lengths``, allocator block tables)
  handed to ``jnp.asarray``/a jitted call without a snapshot while the
  same attribute is mutated elsewhere. ``jnp.asarray`` defers the
  host-buffer read behind the async dispatch queue, so the read races
  the next iteration's mutation and corrupts the step under load.
* **retrace-storm** (FX2xx, `retrace.py`) — ``jax.jit`` wrappers
  constructed per iteration, per-call Python values in static jit
  positions, and shape-polymorphic arguments on serving hot paths —
  each retriggers XLA compilation per step.
* **strategy-validate** (FX3xx, `strategy_check.py`) — the graph-level
  PCG/strategy checker: mesh axes exist, degrees are expressible on
  the mesh, replica dims agree across producer/consumer edges,
  machine bounds hold. Runs inside ``FFModel.compile()`` (typed
  ``StrategyValidationError`` before any XLA lowering) and replays
  over ``search/strategy_io`` JSON files via ``fxlint --strategy``.
* **pallas-gate** (FX4xx, `pallas_gate.py`) — every ``pallas_call``
  module must expose a ``supports()`` geometry gate, cross-module
  kernel calls must sit behind ``supports()``/``use_kernel()`` with a
  dense fallback, and gate constants (sublane alignment, ``_MAX_W``)
  must agree with the kernel-body constants.

CLI: ``python -m flexflow_tpu.analysis`` (diagnostics are
``file:line rule-id message``; a checked-in baseline file absorbs
accepted findings and CI fails on any NEW one — see docs/analysis.md).
"""

from flexflow_tpu.analysis.diagnostics import Diagnostic
from flexflow_tpu.analysis.strategy_check import (
    StrategyDiagnostic,
    StrategyValidationError,
    validate_graph_strategy,
    validate_strategy_doc,
)

__all__ = [
    "Diagnostic",
    "StrategyDiagnostic",
    "StrategyValidationError",
    "validate_graph_strategy",
    "validate_strategy_doc",
]
