"""FX3xx — strategy-validate: typed PCG/strategy diagnostics.

Unity's leverage comes from validating parallelization decisions
BEFORE execution; the failure mode it prevents is an infeasible
annotation surfacing as an opaque XLA/GSPMD error (or worse, a silent
wrong sharding) minutes into a lowering. `validate_graph_strategy`
walks an annotated+propagated PCG and re-derives every constraint the
lowering will rely on, producing typed diagnostics instead:

* **FX301** bad-mesh-axis — a partitioned dim's ``parallel_idx`` names
  no axis of the strategy's mesh.
* **FX302** degree-mesh-mismatch — the degree is not expressible on
  the mesh (not the size of its axis nor a consecutive-axis span
  product; includes one axis claimed by two dims). Decided by the
  SAME ``partition_spec`` lowering the executor runs, so the
  validator never disagrees with the lowering.
* **FX303** non-dividing-degree — a requested degree does not divide
  the dimension it shards (strategy-doc replay; inside a built graph
  ``ParallelDim`` already rejects this at construction).
* **FX304** replica-dim-inconsistency — producer/consumer edges into a
  multi-input elementwise op (or self-attention's q/k/v) disagree on
  (degree, mesh axis, replica degree): GSPMD would insert a hidden
  reshard — or miscompile the op — where the strategy promised none.
* **FX305** machine-bounds — the mesh wants more devices than the
  machine has (the MachineView/submesh bound).
* **FX306** unknown-kind — a strategy file's strategy/site kind is not
  one the loader can rebuild.
* **FX307** bad-degree-value — a degree or mesh axis size below 1.
* **FX308** unknown-op — a strategy file references an op name the
  current graph does not contain.

Serving placement docs (``kind: "serving"``, the files
``FFModel.compile_for_serving`` exports via ``--serve-export-strategy``)
replay through `validate_serving_placement_doc` instead:

* **FX310** bad-serving-mesh — the (data, model) mesh is malformed:
  axes are not exactly ``["data", "model"]``, sizes disagree with
  dp/tp, or a size/host count is below 1.
* **FX311** tp-heads-mismatch — tp does not divide ``num_heads``
  (head-sharded attention weights and K/V pools split the heads dim).
* **FX312** host-shard-mismatch — the page-pool or slot partition does
  not tile across the host count (``num_pages % num_hosts``,
  ``max_seqs % num_hosts``, or a recorded per-host count that does not
  multiply back).

``FFModel.compile()`` runs the graph validator after the final shape
propagation and raises `StrategyValidationError` (a ``ValueError``
carrying ``.diagnostics``) on errors — before any XLA lowering. The
``fxlint --strategy file.json`` mode replays `validate_strategy_doc`
over exported ``search/strategy_io`` files.

Severity: "error" exactly where the executor's lowering would raise
(INPUT outputs and weight shapes — the tensors it materializes with
``partition_spec`` — plus machine bounds); intermediate-activation and
replica-consistency findings are "warning" (GSPMD may legally
reshard). Pipelined strategies demote everything to warnings — the
GPipe executor lowers block weights through its own stacked path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

RULES = {
    "FX301": "partitioned dim references a mesh axis that does not exist",
    "FX302": "degree not expressible on the strategy mesh",
    "FX303": "degree does not divide the dimension it shards",
    "FX304": "replica/parallel dims disagree across a producer/consumer edge",
    "FX305": "mesh exceeds the machine's device count",
    "FX306": "unknown strategy or site kind",
    "FX307": "degree or mesh axis size below 1",
    "FX308": "strategy file references an unknown op",
    "FX310": "serving placement mesh is malformed",
    "FX311": "serving tp degree does not divide the attention head count",
    "FX312": "serving page-pool/slot shards do not match the host count",
}

_DOC_KINDS = ("tp", "seq", "spatial", "pipeline", "mixed")
_SITE_KINDS = (
    "attention",
    "conv_channel",
    "embedding",
    "expert_parallel",
    "linear_chain",
    "single_linear",
)


@dataclasses.dataclass(frozen=True)
class StrategyDiagnostic:
    """One graph/strategy-level finding (node names a PCG op or a
    strategy-file field; '' for mesh-global findings)."""

    rule_id: str
    severity: str  # "error" | "warning"
    node: str
    message: str

    def format(self) -> str:
        where = self.node or "<mesh>"
        return f"{where} {self.rule_id} {self.message}"


class StrategyValidationError(ValueError):
    """compile()-time strategy rejection, raised BEFORE any XLA
    lowering. `.diagnostics` holds the typed findings."""

    def __init__(self, diagnostics: Sequence[StrategyDiagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__(
            "strategy validation failed:\n"
            + "\n".join(d.format() for d in self.diagnostics)
        )


def validate_graph_strategy(
    graph,
    mesh_config,
    num_devices: Optional[int] = None,
    strict_all: bool = False,
) -> List[StrategyDiagnostic]:
    """Validate an annotated+propagated PCG against its mesh. Returns
    every finding; callers decide what severity raises (compile()
    raises on "error"). `num_devices` enables the machine-bounds
    check; `strict_all` promotes intermediate-activation findings to
    errors (the fxlint replay mode's posture)."""
    from flexflow_tpu.core.types import OperatorType

    diags: List[StrategyDiagnostic] = []
    axis_names = tuple(mesh_config.axis_names)
    axis_sizes = tuple(mesh_config.axis_sizes)

    for name, size in zip(axis_names, axis_sizes):
        if size < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX307",
                    "error",
                    "",
                    f"mesh axis '{name}' has size {size} (must be >= 1)",
                )
            )
    if num_devices is not None and mesh_config.num_devices > num_devices:
        diags.append(
            StrategyDiagnostic(
                "FX305",
                "error",
                "",
                f"mesh {dict(zip(axis_names, axis_sizes))} needs "
                f"{mesh_config.num_devices} devices, machine has "
                f"{num_devices}",
            )
        )

    elementwise = {
        t
        for t in (
            getattr(OperatorType, n, None)
            for n in ("EW_ADD", "EW_SUB", "EW_MUL", "EW_DIV", "EW_MAX", "EW_MIN")
        )
        if t is not None
    }

    for guid in graph.topo_order():
        node = graph.nodes[guid]
        is_input = node.op_type == OperatorType.INPUT and not node.inputs
        shapes = [("output", s) for s in node.output_shapes]
        shapes += [("weight", s) for s in node.weight_shapes]
        for kind, shape in shapes:
            strict = strict_all or kind == "weight" or is_input
            sev = "error" if strict else "warning"
            bad_axis = False
            for d in shape.dims:
                if d.degree > 1 and not (
                    0 <= d.parallel_idx < len(axis_names)
                ):
                    bad_axis = True
                    diags.append(
                        StrategyDiagnostic(
                            "FX301",
                            sev,
                            node.name,
                            f"{kind} dim (size {d.size}, degree "
                            f"{d.degree}) references mesh axis "
                            f"{d.parallel_idx} but the mesh has axes "
                            f"{list(axis_names)}",
                        )
                    )
            if bad_axis:
                continue
            # the executor's own lowering decides expressibility — the
            # validator can never disagree with partition_spec
            try:
                shape.partition_spec(axis_names, axis_sizes)
            except ValueError as e:
                diags.append(
                    StrategyDiagnostic(
                        "FX302",
                        sev,
                        node.name,
                        f"{kind} shape {shape} is not expressible on "
                        f"mesh {dict(zip(axis_names, axis_sizes))}: {e}",
                    )
                )

        # replica/parallel-dim agreement across the edges into ops whose
        # inputs must be identically sharded
        check_edges = node.op_type in elementwise or (
            node.op_type == OperatorType.MULTIHEAD_ATTENTION
            and len({(r.guid, r.out_idx) for r in node.inputs}) > 1
        )
        if check_edges and len(node.inputs) >= 2:
            sigs = []
            for ref in node.inputs:
                s = graph.shape_of(ref)
                sigs.append(
                    (
                        tuple((d.degree, d.parallel_idx) for d in s.dims),
                        s.replica_degree,
                    )
                )
            if len(set(sigs)) > 1:
                producers = [
                    graph.nodes[r.guid].name for r in node.inputs
                ]
                diags.append(
                    StrategyDiagnostic(
                        "FX304",
                        "error" if strict_all else "warning",
                        node.name,
                        "inputs disagree on (degree, axis)/replica "
                        f"annotations across producers {producers}: "
                        f"{sigs}",
                    )
                )
    return diags


def validate_serving_placement_doc(
    doc: Dict,
    num_devices: Optional[int] = None,
) -> List[StrategyDiagnostic]:
    """Replay the validator over a serving placement document
    (``kind: "serving"``, exported by ``FFModel.compile_for_serving``
    via ``--serve-export-strategy``; serving/distributed.py
    ``ServingPlacement.to_doc``). Checks the (data, model) mesh shape
    (FX310), tp | num_heads (FX311), and that the page-pool and slot
    partitions tile across the host count (FX312)."""
    diags: List[StrategyDiagnostic] = []

    def _int(value, default=0):
        try:
            return int(value)
        except (TypeError, ValueError):
            return default

    dp = _int(doc.get("dp"), 0)
    tp = _int(doc.get("tp"), 0)
    num_hosts = _int(doc.get("num_hosts"), 0)
    num_heads = _int(doc.get("num_heads"), 0)

    axes = list(doc.get("mesh_axes") or [])
    sizes = [_int(s) for s in (doc.get("mesh_sizes") or [])]
    if axes != ["data", "model"]:
        diags.append(
            StrategyDiagnostic(
                "FX310",
                "error",
                "mesh_axes",
                f"serving mesh axes must be ['data', 'model'], got {axes}",
            )
        )
    if sizes != [dp, tp]:
        diags.append(
            StrategyDiagnostic(
                "FX310",
                "error",
                "mesh_sizes",
                f"mesh_sizes {sizes} disagree with dp={dp}, tp={tp}",
            )
        )
    for name, value in (("dp", dp), ("tp", tp), ("num_hosts", num_hosts)):
        if value < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX310",
                    "error",
                    name,
                    f"{name}={value} (must be >= 1)",
                )
            )
    if num_devices is not None and dp >= 1 and tp >= 1:
        if dp * tp > num_devices:
            diags.append(
                StrategyDiagnostic(
                    "FX305",
                    "error",
                    "",
                    f"serving mesh (data={dp}, model={tp}) needs "
                    f"{dp * tp} devices, machine has {num_devices}",
                )
            )
    if tp >= 1 and num_heads >= 1 and num_heads % tp:
        diags.append(
            StrategyDiagnostic(
                "FX311",
                "error",
                "tp",
                f"tp={tp} does not divide num_heads={num_heads}",
            )
        )

    def _check_partition(section, total_key, per_host_key):
        block = doc.get(section)
        if not block or num_hosts < 1:
            return
        total = _int(block.get(total_key), 0)
        per_host = _int(block.get(per_host_key), -1)
        if total % num_hosts:
            diags.append(
                StrategyDiagnostic(
                    "FX312",
                    "error",
                    section,
                    f"{total_key}={total} is not divisible by "
                    f"num_hosts={num_hosts}",
                )
            )
        elif per_host >= 0 and per_host * num_hosts != total:
            diags.append(
                StrategyDiagnostic(
                    "FX312",
                    "error",
                    section,
                    f"{per_host_key}={per_host} x num_hosts={num_hosts} "
                    f"!= {total_key}={total}",
                )
            )

    _check_partition("page_pool", "num_pages", "pages_per_host")
    _check_partition("slots", "max_seqs", "slots_per_host")
    return diags


def validate_strategy_doc(
    doc: Dict,
    graph=None,
    num_devices: Optional[int] = None,
) -> List[StrategyDiagnostic]:
    """Replay the validator over an exported strategy JSON document
    (search/strategy_io format) — the ``fxlint --strategy`` mode. With
    a graph, additionally checks site op names and dp divisibility.
    Serving placement docs (``kind: "serving"``) route to
    `validate_serving_placement_doc`."""
    kind = doc.get("kind", "tp")
    if kind == "serving":
        return validate_serving_placement_doc(doc, num_devices=num_devices)
    diags: List[StrategyDiagnostic] = []
    if kind not in _DOC_KINDS:
        diags.append(
            StrategyDiagnostic(
                "FX306",
                "error",
                "kind",
                f"unknown strategy kind {kind!r} (known: {_DOC_KINDS})",
            )
        )
    extra = doc.get("extra", {}) or {}
    mesh_sizes = doc.get("mesh_sizes") or []

    def _deg(value, default=1):
        return default if value is None else int(value)

    degrees = {
        "dp": _deg(doc.get("dp", mesh_sizes[0] if mesh_sizes else None)),
        "tp": _deg(doc.get("tp")),
    }
    for k in ("sp", "hp", "pp"):
        if k in extra:
            degrees[k] = int(extra[k])
    for name, deg in degrees.items():
        if deg < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX307", "error", name, f"{name}={deg} (must be >= 1)"
                )
            )
    for size in mesh_sizes:
        if int(size) < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX307",
                    "error",
                    "mesh_sizes",
                    f"mesh axis size {size} (must be >= 1)",
                )
            )
    if num_devices is not None:
        want = max(1, degrees["dp"]) * max(
            1,
            degrees.get("tp", 1)
            * degrees.get("sp", 1)
            * degrees.get("hp", 1)
            * degrees.get("pp", 1),
        )
        if want > num_devices:
            diags.append(
                StrategyDiagnostic(
                    "FX305",
                    "error",
                    "",
                    f"strategy wants {want} devices, machine has "
                    f"{num_devices}",
                )
            )
    names_in_graph = (
        {n.name for n in graph.nodes.values()} if graph is not None else None
    )
    for i, site in enumerate(doc.get("sites", []) or []):
        skind = site.get("kind")
        if skind not in _SITE_KINDS:
            diags.append(
                StrategyDiagnostic(
                    "FX306",
                    "error",
                    f"sites[{i}]",
                    f"unknown site kind {skind!r} (known: {_SITE_KINDS})",
                )
            )
        if names_in_graph is not None:
            for nm in site.get("names", []):
                if nm not in names_in_graph:
                    diags.append(
                        StrategyDiagnostic(
                            "FX308",
                            "error",
                            f"sites[{i}]",
                            f"references op {nm!r} not present in the "
                            "graph",
                        )
                    )
    if graph is not None and degrees["dp"] > 1:
        from flexflow_tpu.core.types import OperatorType

        for node in graph.nodes.values():
            if node.op_type == OperatorType.INPUT and not node.inputs:
                shape = node.params.get("shape") or (
                    node.output_shapes[0] if node.output_shapes else None
                )
                if shape is None:
                    continue
                batch = shape.dims[0].size
                if batch % degrees["dp"]:
                    diags.append(
                        StrategyDiagnostic(
                            "FX303",
                            "error",
                            node.name,
                            f"dp={degrees['dp']} does not divide input "
                            f"batch {batch}",
                        )
                    )
    return diags
