"""FX3xx — strategy-validate: typed PCG/strategy diagnostics.

Unity's leverage comes from validating parallelization decisions
BEFORE execution; the failure mode it prevents is an infeasible
annotation surfacing as an opaque XLA/GSPMD error (or worse, a silent
wrong sharding) minutes into a lowering. `validate_graph_strategy`
walks an annotated+propagated PCG and re-derives every constraint the
lowering will rely on, producing typed diagnostics instead:

* **FX301** bad-mesh-axis — a partitioned dim's ``parallel_idx`` names
  no axis of the strategy's mesh.
* **FX302** degree-mesh-mismatch — the degree is not expressible on
  the mesh (not the size of its axis nor a consecutive-axis span
  product; includes one axis claimed by two dims). Decided by the
  SAME ``partition_spec`` lowering the executor runs, so the
  validator never disagrees with the lowering.
* **FX303** non-dividing-degree — a requested degree does not divide
  the dimension it shards (strategy-doc replay; inside a built graph
  ``ParallelDim`` already rejects this at construction).
* **FX304** replica-dim-inconsistency — producer/consumer edges into a
  multi-input elementwise op (or self-attention's q/k/v) disagree on
  (degree, mesh axis, replica degree): GSPMD would insert a hidden
  reshard — or miscompile the op — where the strategy promised none.
* **FX305** machine-bounds — the mesh wants more devices than the
  machine has (the MachineView/submesh bound).
* **FX306** unknown-kind — a strategy file's strategy/site kind is not
  one the loader can rebuild.
* **FX307** bad-degree-value — a degree or mesh axis size below 1.
* **FX308** unknown-op — a strategy file references an op name the
  current graph does not contain.

``FFModel.compile()`` runs the graph validator after the final shape
propagation and raises `StrategyValidationError` (a ``ValueError``
carrying ``.diagnostics``) on errors — before any XLA lowering. The
``fxlint --strategy file.json`` mode replays `validate_strategy_doc`
over exported ``search/strategy_io`` files.

Severity: "error" exactly where the executor's lowering would raise
(INPUT outputs and weight shapes — the tensors it materializes with
``partition_spec`` — plus machine bounds); intermediate-activation and
replica-consistency findings are "warning" (GSPMD may legally
reshard). Pipelined strategies demote everything to warnings — the
GPipe executor lowers block weights through its own stacked path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

RULES = {
    "FX301": "partitioned dim references a mesh axis that does not exist",
    "FX302": "degree not expressible on the strategy mesh",
    "FX303": "degree does not divide the dimension it shards",
    "FX304": "replica/parallel dims disagree across a producer/consumer edge",
    "FX305": "mesh exceeds the machine's device count",
    "FX306": "unknown strategy or site kind",
    "FX307": "degree or mesh axis size below 1",
    "FX308": "strategy file references an unknown op",
}

_DOC_KINDS = ("tp", "seq", "spatial", "pipeline", "mixed")
_SITE_KINDS = (
    "attention",
    "conv_channel",
    "embedding",
    "expert_parallel",
    "linear_chain",
    "single_linear",
)


@dataclasses.dataclass(frozen=True)
class StrategyDiagnostic:
    """One graph/strategy-level finding (node names a PCG op or a
    strategy-file field; '' for mesh-global findings)."""

    rule_id: str
    severity: str  # "error" | "warning"
    node: str
    message: str

    def format(self) -> str:
        where = self.node or "<mesh>"
        return f"{where} {self.rule_id} {self.message}"


class StrategyValidationError(ValueError):
    """compile()-time strategy rejection, raised BEFORE any XLA
    lowering. `.diagnostics` holds the typed findings."""

    def __init__(self, diagnostics: Sequence[StrategyDiagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__(
            "strategy validation failed:\n"
            + "\n".join(d.format() for d in self.diagnostics)
        )


def validate_graph_strategy(
    graph,
    mesh_config,
    num_devices: Optional[int] = None,
    strict_all: bool = False,
) -> List[StrategyDiagnostic]:
    """Validate an annotated+propagated PCG against its mesh. Returns
    every finding; callers decide what severity raises (compile()
    raises on "error"). `num_devices` enables the machine-bounds
    check; `strict_all` promotes intermediate-activation findings to
    errors (the fxlint replay mode's posture)."""
    from flexflow_tpu.core.types import OperatorType

    diags: List[StrategyDiagnostic] = []
    axis_names = tuple(mesh_config.axis_names)
    axis_sizes = tuple(mesh_config.axis_sizes)

    for name, size in zip(axis_names, axis_sizes):
        if size < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX307",
                    "error",
                    "",
                    f"mesh axis '{name}' has size {size} (must be >= 1)",
                )
            )
    if num_devices is not None and mesh_config.num_devices > num_devices:
        diags.append(
            StrategyDiagnostic(
                "FX305",
                "error",
                "",
                f"mesh {dict(zip(axis_names, axis_sizes))} needs "
                f"{mesh_config.num_devices} devices, machine has "
                f"{num_devices}",
            )
        )

    elementwise = {
        t
        for t in (
            getattr(OperatorType, n, None)
            for n in ("EW_ADD", "EW_SUB", "EW_MUL", "EW_DIV", "EW_MAX", "EW_MIN")
        )
        if t is not None
    }

    for guid in graph.topo_order():
        node = graph.nodes[guid]
        is_input = node.op_type == OperatorType.INPUT and not node.inputs
        shapes = [("output", s) for s in node.output_shapes]
        shapes += [("weight", s) for s in node.weight_shapes]
        for kind, shape in shapes:
            strict = strict_all or kind == "weight" or is_input
            sev = "error" if strict else "warning"
            bad_axis = False
            for d in shape.dims:
                if d.degree > 1 and not (
                    0 <= d.parallel_idx < len(axis_names)
                ):
                    bad_axis = True
                    diags.append(
                        StrategyDiagnostic(
                            "FX301",
                            sev,
                            node.name,
                            f"{kind} dim (size {d.size}, degree "
                            f"{d.degree}) references mesh axis "
                            f"{d.parallel_idx} but the mesh has axes "
                            f"{list(axis_names)}",
                        )
                    )
            if bad_axis:
                continue
            # the executor's own lowering decides expressibility — the
            # validator can never disagree with partition_spec
            try:
                shape.partition_spec(axis_names, axis_sizes)
            except ValueError as e:
                diags.append(
                    StrategyDiagnostic(
                        "FX302",
                        sev,
                        node.name,
                        f"{kind} shape {shape} is not expressible on "
                        f"mesh {dict(zip(axis_names, axis_sizes))}: {e}",
                    )
                )

        # replica/parallel-dim agreement across the edges into ops whose
        # inputs must be identically sharded
        check_edges = node.op_type in elementwise or (
            node.op_type == OperatorType.MULTIHEAD_ATTENTION
            and len({(r.guid, r.out_idx) for r in node.inputs}) > 1
        )
        if check_edges and len(node.inputs) >= 2:
            sigs = []
            for ref in node.inputs:
                s = graph.shape_of(ref)
                sigs.append(
                    (
                        tuple((d.degree, d.parallel_idx) for d in s.dims),
                        s.replica_degree,
                    )
                )
            if len(set(sigs)) > 1:
                producers = [
                    graph.nodes[r.guid].name for r in node.inputs
                ]
                diags.append(
                    StrategyDiagnostic(
                        "FX304",
                        "error" if strict_all else "warning",
                        node.name,
                        "inputs disagree on (degree, axis)/replica "
                        f"annotations across producers {producers}: "
                        f"{sigs}",
                    )
                )
    return diags


def validate_strategy_doc(
    doc: Dict,
    graph=None,
    num_devices: Optional[int] = None,
) -> List[StrategyDiagnostic]:
    """Replay the validator over an exported strategy JSON document
    (search/strategy_io format) — the ``fxlint --strategy`` mode. With
    a graph, additionally checks site op names and dp divisibility."""
    diags: List[StrategyDiagnostic] = []
    kind = doc.get("kind", "tp")
    if kind not in _DOC_KINDS:
        diags.append(
            StrategyDiagnostic(
                "FX306",
                "error",
                "kind",
                f"unknown strategy kind {kind!r} (known: {_DOC_KINDS})",
            )
        )
    extra = doc.get("extra", {}) or {}
    mesh_sizes = doc.get("mesh_sizes") or []

    def _deg(value, default=1):
        return default if value is None else int(value)

    degrees = {
        "dp": _deg(doc.get("dp", mesh_sizes[0] if mesh_sizes else None)),
        "tp": _deg(doc.get("tp")),
    }
    for k in ("sp", "hp", "pp"):
        if k in extra:
            degrees[k] = int(extra[k])
    for name, deg in degrees.items():
        if deg < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX307", "error", name, f"{name}={deg} (must be >= 1)"
                )
            )
    for size in mesh_sizes:
        if int(size) < 1:
            diags.append(
                StrategyDiagnostic(
                    "FX307",
                    "error",
                    "mesh_sizes",
                    f"mesh axis size {size} (must be >= 1)",
                )
            )
    if num_devices is not None:
        want = max(1, degrees["dp"]) * max(
            1,
            degrees.get("tp", 1)
            * degrees.get("sp", 1)
            * degrees.get("hp", 1)
            * degrees.get("pp", 1),
        )
        if want > num_devices:
            diags.append(
                StrategyDiagnostic(
                    "FX305",
                    "error",
                    "",
                    f"strategy wants {want} devices, machine has "
                    f"{num_devices}",
                )
            )
    names_in_graph = (
        {n.name for n in graph.nodes.values()} if graph is not None else None
    )
    for i, site in enumerate(doc.get("sites", []) or []):
        skind = site.get("kind")
        if skind not in _SITE_KINDS:
            diags.append(
                StrategyDiagnostic(
                    "FX306",
                    "error",
                    f"sites[{i}]",
                    f"unknown site kind {skind!r} (known: {_SITE_KINDS})",
                )
            )
        if names_in_graph is not None:
            for nm in site.get("names", []):
                if nm not in names_in_graph:
                    diags.append(
                        StrategyDiagnostic(
                            "FX308",
                            "error",
                            f"sites[{i}]",
                            f"references op {nm!r} not present in the "
                            "graph",
                        )
                    )
    if graph is not None and degrees["dp"] > 1:
        from flexflow_tpu.core.types import OperatorType

        for node in graph.nodes.values():
            if node.op_type == OperatorType.INPUT and not node.inputs:
                shape = node.params.get("shape") or (
                    node.output_shapes[0] if node.output_shapes else None
                )
                if shape is None:
                    continue
                batch = shape.dims[0].size
                if batch % degrees["dp"]:
                    diags.append(
                        StrategyDiagnostic(
                            "FX303",
                            "error",
                            node.name,
                            f"dp={degrees['dp']} does not divide input "
                            f"batch {batch}",
                        )
                    )
    return diags
