"""FX4xx — pallas-gate: every kernel sits behind a geometry gate.

The Pallas kernels (ops/pallas/) only take certain geometries
(sublane-aligned head_dim/page/chunk, ``w <= _MAX_W``); everything
else must route to the dense jnp paths. The contract has two halves —
a ``supports()`` predicate next to the kernel, and callers that
consult it before dispatching — and it decays in two ways: a new
kernel ships without a gate, or the gate's constants drift from the
kernel body's BlockSpec constants. Rules:

* **FX401** — a module contains ``pallas_call`` but defines no
  ``supports()`` predicate: the kernel has no geometry gate for
  callers to consult.
* **FX402** — gate-constant drift: ``SUBLANES``/``LANES`` values
  disagree across kernel modules, or a kernel module defines an
  alignment/width constant (``SUBLANES``, ``_MAX_W``) that its own
  ``supports()`` never references (the gate and the kernel body can
  then diverge silently).
* **FX403** — a cross-module call to a kernel entry point from a
  function with no ``supports()``/``use_kernel()`` gate: rejected
  geometries would reach the kernel and die inside Mosaic instead of
  falling back to dense. Public callers need the gate in the SAME
  function; private helpers (``_name``) may rely on a gate elsewhere
  in their module (e.g. ring_attention's ``_pallas_ok``).

Kernel entry points are computed, not hardcoded: the functions of a
``pallas_call`` module that (transitively, within the module) reach a
``pallas_call``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set

from flexflow_tpu.analysis.diagnostics import Diagnostic, name_chain

RULES = {
    "FX401": "pallas_call module without a supports() geometry gate",
    "FX402": "gate constants drift from kernel-body constants",
    "FX403": "cross-module kernel call without a supports()/use_kernel() gate",
}

_GATE_CONSTANTS = ("SUBLANES", "LANES")
_SUPPORTS_MUST_USE = ("SUBLANES", "_MAX_W")


def _contains_pallas_call(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = name_chain(node.func)
            if chain and chain[-1] == "pallas_call":
                return True
    return False


def _module_constants(tree: ast.Module) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not (
            isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, int)
        ):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = node.value.value
    return out


def _calls_in(node: ast.AST) -> Set[str]:
    """Last-element names of every call target in the subtree."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            chain = name_chain(n.func)
            if chain:
                out.add(chain[-1])
    return out


def _kernel_entries(tree: ast.Module) -> Set[str]:
    """Functions of a kernel module that reach pallas_call (directly or
    through same-module calls) — the names outside callers must gate."""
    funcs = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }
    entries = {
        name
        for name, fn in funcs.items()
        if _contains_pallas_call(fn)
    }
    changed = True
    while changed:
        changed = False
        for name, fn in funcs.items():
            if name in entries:
                continue
            if _calls_in(fn) & entries:
                entries.add(name)
                changed = True
    return entries


def _gate_present(names: Set[str]) -> bool:
    return any("supports" in n or n == "use_kernel" for n in names)


def run(trees: Dict[str, ast.Module]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    kernel_modules: Dict[str, ast.Module] = {}
    constants: Dict[str, Dict[str, int]] = {}
    entries_by_module: Dict[str, Set[str]] = {}

    for path, tree in trees.items():
        if _contains_pallas_call(tree):
            kernel_modules[path] = tree
            entries_by_module[path] = _kernel_entries(tree)
        consts = _module_constants(tree)
        if any(c in consts for c in _GATE_CONSTANTS):
            constants[path] = consts

    # FX401 + the supports-uses-its-constants half of FX402
    for path, tree in kernel_modules.items():
        supports_fns = [
            n
            for n in tree.body
            if isinstance(n, ast.FunctionDef) and "supports" in n.name
        ]
        if not supports_fns:
            diags.append(
                Diagnostic(
                    "FX401",
                    path,
                    1,
                    "module contains pallas_call but defines no "
                    "supports() geometry gate — callers cannot fall "
                    "back to dense",
                )
            )
            continue
        referenced: Set[str] = set()
        for fn in supports_fns:
            for node in ast.walk(fn):
                if isinstance(node, ast.Name):
                    referenced.add(node.id)
        consts = _module_constants(tree)
        for c in _SUPPORTS_MUST_USE:
            if c in consts and c not in referenced:
                diags.append(
                    Diagnostic(
                        "FX402",
                        path,
                        1,
                        f"kernel module defines {c} but supports() "
                        "never references it — the gate can drift from "
                        "the kernel body's constants",
                    )
                )

    # cross-module constant agreement (FX402)
    for const in _GATE_CONSTANTS:
        values = {
            path: consts[const]
            for path, consts in constants.items()
            if const in consts
        }
        if len(set(values.values())) > 1:
            detail = ", ".join(
                f"{os.path.basename(p)}={v}" for p, v in sorted(values.items())
            )
            for path in values:
                diags.append(
                    Diagnostic(
                        "FX402",
                        path,
                        1,
                        f"gate constant {const} disagrees across kernel "
                        f"modules ({detail})",
                    )
                )

    # FX403: cross-module kernel-entry calls must be gated
    entry_owner: Dict[str, str] = {}
    for path, entries in entries_by_module.items():
        for name in entries:
            entry_owner[name] = path
    if entry_owner:
        for path, tree in trees.items():
            module_gated = _gate_present(_calls_in(tree))
            # top-level functions and methods only: a nested closure's
            # calls are attributed to its enclosing function, which owns
            # the gate-or-not decision
            top_level: List[ast.FunctionDef] = []
            for n in tree.body:
                if isinstance(n, ast.FunctionDef):
                    top_level.append(n)
                elif isinstance(n, ast.ClassDef):
                    top_level.extend(
                        m for m in n.body if isinstance(m, ast.FunctionDef)
                    )
            for fn in top_level:
                calls = _calls_in(fn)
                targets = {
                    c
                    for c in calls
                    if c in entry_owner and entry_owner[c] != path
                }
                if not targets:
                    continue
                gated = _gate_present(calls) or (
                    fn.name.startswith("_") and module_gated
                )
                if not gated:
                    diags.append(
                        Diagnostic(
                            "FX403",
                            path,
                            fn.lineno,
                            f"'{fn.name}' calls kernel entry "
                            f"{sorted(targets)} without a supports()/"
                            "use_kernel() gate — rejected geometries "
                            "reach the kernel instead of the dense "
                            "fallback",
                        )
                    )
    return diags
