"""Shared train-step timing for the benchmark surfaces (bench.py,
scripts/bench_configs.py, scripts/calibrate.py callers).

Methodology (see BASELINE.md): on the tunneled TPU platform
`block_until_ready` does not synchronize with remote execution, a
device->host readback carries a large constant RTT, and host-side
dispatch chains longer than ~25 steps can overflow the tunnel queue.
So the N-step loop runs INSIDE one jitted program (`lax.scan` over the
train step — the analog of the reference's Legion begin/end_trace
replay loop, transformer.cc:192-198), ended by a scalar readback that
forces the whole chain; two chain lengths are differenced so RTT and
dispatch constants cancel, and the measurement repeats `reps` times
taking the MIN (the tunnel adds contention spikes, never speedups).
"""

from __future__ import annotations

import time


def _adaptive_differenced(
    make_chain, run_args, n1, n2, reps, cap=20000, rep_sleep_s=0.0
):
    """Differenced timing with the adaptive-window guard: grow the chain
    until the differenced window dominates the tunnel's per-call jitter
    (sub-ms steps — e.g. the sparse-embedding DLRM at ~26 us — sit below
    it at short chains). A measurement that stays non-positive at the cap
    is reported as NaN, never as a negative time."""
    import numpy as np

    while True:
        r1, r2 = make_chain(n1), make_chain(n2)
        _ = float(np.asarray(r1(*run_args)))  # compile + warmup
        _ = float(np.asarray(r2(*run_args)))
        best1 = best2 = float("inf")
        for _i in range(reps):
            if rep_sleep_s and _i:
                # tunnel/chip contention comes in seconds-long bursts;
                # spacing the reps lets min() catch a clean window
                time.sleep(rep_sleep_s)
            t0 = time.perf_counter()
            _ = float(np.asarray(r1(*run_args)))
            t1 = time.perf_counter()
            _ = float(np.asarray(r2(*run_args)))
            t2 = time.perf_counter()
            # min each window SEPARATELY, then difference: min of the
            # per-rep difference is biased LOW by contention spikes
            # landing in the short chain (a spike in t1-t0 fakes a
            # speedup), which min() then selects for
            best1 = min(best1, t1 - t0)
            best2 = min(best2, t2 - t1)
        best = (best2 - best1) / (n2 - n1)
        window = best * (n2 - n1)
        if window >= 0.05:
            return best
        if n2 >= cap:
            return best if best > 0 else float("nan")
        n1 *= 10
        n2 *= 10


def measure_train_step(
    model, batch, n1: int = 5, n2: int = 20, reps: int = 6,
    rep_sleep_s: float = 0.0, estimates: int = 1,
):
    """Differenced per-train-step seconds via on-device lax.scan chains.

    `batch` must already be sharded (executor.shard_batch).

    estimates > 1: run the whole adaptive differencing that many times
    (spaced) and take the MEDIAN — independent in-process estimates
    catch the seconds-long tunnel-contention bursts that otherwise
    poison a whole invocation of the cross-process protocol (the
    round-3 mT5 118% / DLRM 96% spreads were single contaminated
    invocations). Median, not min: a burst landing selectively in one
    estimate's SHORT chain biases that estimate LOW, and min() would
    select exactly the contaminated one (the same asymmetry the
    per-window-min rule in _adaptive_differenced exists to avoid)."""
    import statistics
    import time as _time

    import jax
    from jax import lax

    step_fn = model.executor.train_step_fn()
    key = jax.random.PRNGKey(0)

    def chain(n):
        @jax.jit
        def run(p, o):
            def body(c, _):
                cp, co = c
                p2, o2, loss, _ = step_fn(cp, co, batch, key)
                return (p2, o2), loss

            _, losses = lax.scan(body, (p, o), None, length=n)
            return losses[-1]

        return run

    vals = []
    for e in range(max(1, estimates)):
        if e:
            _time.sleep(3.0)
        t = _adaptive_differenced(
            chain, (model.params, model.opt_state), n1, n2, reps,
            rep_sleep_s=rep_sleep_s,
        )
        if t == t:  # NaN-safe
            vals.append(t)
    return statistics.median(vals) if vals else float("nan")


def measure_fn(fn, args, n1: int = 4, n2: int = 12, reps: int = 3):
    """Differenced per-call seconds of an arbitrary jittable fn(*args),
    chained on-device with a data dependency between iterations so XLA
    cannot hoist the body; same adaptive-window guard as
    measure_train_step."""
    import jax
    from jax import lax

    def chain(n):
        @jax.jit
        def run(*a):
            def body(c, _):
                out = fn(*c)
                dep = (out.sum() * 1e-12).astype(c[0].dtype)
                return (c[0] + dep, *c[1:]), out.sum()

            _, s = lax.scan(body, a, None, length=n)
            return s[-1]

        return run

    return _adaptive_differenced(chain, tuple(args), n1, n2, reps, cap=1200)
