"""Shared train-step timing for the benchmark surfaces (bench.py,
scripts/bench_configs.py, scripts/calibrate.py callers).

Methodology (see BASELINE.md): on the tunneled TPU platform
`block_until_ready` does not synchronize with remote execution, a
device->host readback carries a large constant RTT, and host-side
dispatch chains longer than ~25 steps can overflow the tunnel queue.
So the N-step loop runs INSIDE one jitted program (`lax.scan` over the
train step — the analog of the reference's Legion begin/end_trace
replay loop, transformer.cc:192-198), ended by a scalar readback that
forces the whole chain; two chain lengths are differenced so RTT and
dispatch constants cancel, and the measurement repeats `reps` times
taking the MIN (the tunnel adds contention spikes, never speedups).
"""

from __future__ import annotations

import time


def measure_train_step(model, batch, n1: int = 5, n2: int = 20, reps: int = 6):
    """Differenced per-train-step seconds via on-device lax.scan chains.

    `batch` must already be sharded (executor.shard_batch)."""
    import jax
    import numpy as np
    from jax import lax

    step_fn = model.executor.train_step_fn()
    key = jax.random.PRNGKey(0)

    def chain(n):
        @jax.jit
        def run(p, o):
            def body(c, _):
                cp, co = c
                p2, o2, loss, _ = step_fn(cp, co, batch, key)
                return (p2, o2), loss

            _, losses = lax.scan(body, (p, o), None, length=n)
            return losses[-1]

        return run

    r1, r2 = chain(n1), chain(n2)
    p, o = model.params, model.opt_state
    _ = float(np.asarray(r1(p, o)))  # compile + warmup
    _ = float(np.asarray(r2(p, o)))
    best = float("inf")
    for _i in range(reps):
        t0 = time.perf_counter()
        _ = float(np.asarray(r1(p, o)))
        t1 = time.perf_counter()
        _ = float(np.asarray(r2(p, o)))
        t2 = time.perf_counter()
        best = min(best, ((t2 - t1) - (t1 - t0)) / (n2 - n1))
    return best
