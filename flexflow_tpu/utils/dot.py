"""DOT export of the PCG and the simulated task graph (reference:
src/utils/dot/, graph.cc export_strategy_computation_graph — the
--compgraph / --taskgraph / --include-costs-dot-graph artifacts,
SURVEY §2.1/§5)."""

from __future__ import annotations

from typing import Dict, Optional

from flexflow_tpu.core.pcg import PCGGraph


def pcg_to_dot(
    graph: PCGGraph,
    include_costs: bool = False,
    spec=None,
    machine_model=None,
) -> str:
    """include_costs annotates each node with the analytic roofline cost
    (reference: --include-costs-dot-graph) — costed with the caller's
    machine description so the artifact matches what the search saw."""
    cost_of = {}
    if include_costs:
        from flexflow_tpu.core.machine import MachineSpec
        from flexflow_tpu.search.cost_model import CostModel

        cm = CostModel(spec or MachineSpec(), machine_model=machine_model)
        for guid in graph.topo_order():
            node = graph.nodes[guid]
            if node.inputs and not node.is_parallel_op:
                in_shapes = [graph.shape_of(r) for r in node.inputs]
                try:
                    c = cm.op_cost(node, in_shapes)
                    cost_of[guid] = c.forward_time + c.backward_time
                except Exception:
                    pass

    lines = ["digraph PCG {", "  rankdir=TB;"]
    for guid in graph.topo_order():
        node = graph.nodes[guid]
        shape_str = ", ".join(str(s) for s in node.output_shapes)
        mv = ""
        if node.machine_view is not None:
            mv = f"\\nview={node.machine_view.dims}@{node.machine_view.start_device_id}"
        cost = ""
        if guid in cost_of:
            cost = f"\\ncost={cost_of[guid] * 1e6:.1f}us"
        color = "lightblue" if node.is_parallel_op else "white"
        lines.append(
            f'  n{guid} [label="{node.name}\\n{node.op_type.name}'
            f'\\n{shape_str}{mv}{cost}", style=filled, fillcolor={color}, shape=box];'
        )
        for ref in node.inputs:
            lines.append(f"  n{ref.guid} -> n{guid};")
    lines.append("}")
    return "\n".join(lines)


def export_pcg_dot(
    graph: PCGGraph,
    path: str,
    include_costs: bool = False,
    spec=None,
    machine_model=None,
):
    with open(path, "w") as f:
        f.write(pcg_to_dot(graph, include_costs, spec, machine_model))


def task_graph_to_dot(export: Dict) -> str:
    """Render the simulator's SimTask DAG (reference: the --taskgraph dump
    of simulate_runtime, simulator.h:715). `export` is the dict filled by
    estimate_graph_cost(..., export=...): resource_of / duration / names /
    edges / num_resources."""
    res_color = ["white", "lightyellow", "lightpink", "lightcyan"]
    lines = ["digraph TaskGraph {", "  rankdir=LR;"]
    for i, (r, d, name) in enumerate(
        zip(export["resource_of"], export["duration"], export["names"])
    ):
        kind = "chip" if r == 0 else f"link{r - 1}"
        color = res_color[min(r, len(res_color) - 1)]
        lines.append(
            f'  t{i} [label="{name}\\n{kind} {d * 1e6:.1f}us", '
            f"style=filled, fillcolor={color}, shape=box];"
        )
    for s, d in export["edges"]:
        lines.append(f"  t{s} -> t{d};")
    lines.append("}")
    return "\n".join(lines)


def export_task_graph_dot(
    graph: PCGGraph, path: str, mesh_sizes, spec=None, machine_model=None
):
    """Build the simulated task graph for the CURRENT annotated PCG and
    write it as DOT (the --taskgraph artifact)."""
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.simulator import estimate_graph_cost

    cm = CostModel(spec or MachineSpec(), machine_model=machine_model)
    export: Dict = {}
    estimate_graph_cost(graph, cm, mesh_sizes, export=export)
    with open(path, "w") as f:
        f.write(task_graph_to_dot(export))
