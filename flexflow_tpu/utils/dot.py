"""DOT export of the PCG (reference: src/utils/dot/, graph.cc print_dot —
the --compgraph/--taskgraph artifacts, SURVEY §2.1)."""

from __future__ import annotations

from flexflow_tpu.core.pcg import PCGGraph


def pcg_to_dot(graph: PCGGraph, include_costs: bool = False) -> str:
    lines = ["digraph PCG {", "  rankdir=TB;"]
    for guid in graph.topo_order():
        node = graph.nodes[guid]
        shape_str = ", ".join(str(s) for s in node.output_shapes)
        mv = ""
        if node.machine_view is not None:
            mv = f"\\nview={node.machine_view.dims}@{node.machine_view.start_device_id}"
        color = "lightblue" if node.is_parallel_op else "white"
        lines.append(
            f'  n{guid} [label="{node.name}\\n{node.op_type.name}'
            f'\\n{shape_str}{mv}", style=filled, fillcolor={color}, shape=box];'
        )
        for ref in node.inputs:
            lines.append(f"  n{ref.guid} -> n{guid};")
    lines.append("}")
    return "\n".join(lines)


def export_pcg_dot(graph: PCGGraph, path: str, include_costs: bool = False):
    with open(path, "w") as f:
        f.write(pcg_to_dot(graph, include_costs))
