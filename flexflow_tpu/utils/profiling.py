"""Profiling utilities.

Rebuild of the reference's --profiling path (reference: FFConfig.profiling
→ Op.profiling → per-kernel cudaEvent timing printed per task,
kernels/linear_kernels.cu:95-117; SURVEY §5.1). Two TPU-native tools:

  * `profile_operators(model, batch)` — time each PCG node's lowered
    forward in isolation (jitted per-op microbench on its shard shapes)
    and return/print a per-op table. Isolated-op times over-count what
    XLA fusion removes from the real step (the same caveat the cost
    model documents), so treat them as relative weights.
  * `trace(dir)` — context manager around jax.profiler for a real XLA
    trace (the analog of `-lg:prof` external profiles, viewable in
    TensorBoard / Perfetto).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple


def profile_operators(
    model, batch: Dict, iters: int = 5, verbose: bool = True
) -> List[Tuple[str, float]]:
    """Per-op isolated forward times in seconds, slowest first."""
    import jax

    ex = model.executor
    if ex is None:
        raise RuntimeError("call compile() before profile_operators()")
    sharded = ex.shard_batch(batch)
    values = {}
    rows: List[Tuple[str, float]] = []
    from flexflow_tpu.core.types import OperatorType
    from flexflow_tpu.ops.registry import LowerCtx

    for guid in ex.topo:
        node = ex.graph.nodes[guid]
        if node.op_type in (OperatorType.INPUT, OperatorType.NOOP) and not node.inputs:
            if node.name not in sharded:
                raise KeyError(f"batch missing input '{node.name}'")
            values[(guid, 0)] = sharded[node.name]
            continue
        ins = [values[(r.guid, r.out_idx)] for r in node.inputs]
        # per-weight accessor: pipelined trunks store weights stacked
        # under their template guid (Executor.get_host_param slices out
        # this block's weights; plain executors read params[guid] direct)
        ws = [
            ex.get_host_param(model.params, guid, i)
            for i in range(len(node.weight_shapes))
        ]
        # mirror Executor.forward_values' ctx so profiled shapes match the
        # real step (seq_length truncation included)
        ctx = LowerCtx(
            train=False,
            rng=None,
            mesh=ex.mesh,
            axis_names=ex.mesh_config.axis_names,
            in_shapes=[ex.graph.shape_of(r) for r in node.inputs],
            bf16_matmul=ex.mixed_precision,
            seq_length=ex.seq_length,
        )
        fn = ex._lowered[guid]
        jitted = jax.jit(lambda i, w, _fn=fn, _ctx=ctx: _fn(i, w, _ctx))
        outs = jitted(ins, ws)
        jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(iters):
            outs = jitted(ins, ws)
        jax.block_until_ready(outs)
        dt = (time.perf_counter() - t0) / iters
        rows.append((node.name, dt))
        for i, out in enumerate(outs):
            values[(guid, i)] = out
    rows.sort(key=lambda r: -r[1])
    if verbose:
        total = sum(t for _, t in rows) or 1e-12
        print(f"{'op':<32} {'time':>12} {'share':>7}")
        for name, t in rows:
            print(f"{name:<32} {t * 1e6:>10.1f}us {t / total:>6.1%}")
    return rows


def xla_cost_analysis(model, batch: Dict) -> Dict[str, float]:
    """XLA's own cost analysis of the compiled train step — flops,
    bytes accessed, and transcendentals as the COMPILER counts them
    after fusion/DCE (the ground truth the analytic cost model
    approximates; the reference has no equivalent, its simulator only
    times kernels). Returns the cost dict of `Compiled.cost_analysis()`.

        model.compile(...); xla_cost_analysis(model, batch)
        # {'flops': 2.1e9, 'bytes accessed': 8.4e8, ...}
    """
    import jax

    ex = model.executor
    if ex is None:
        raise RuntimeError("call compile() before xla_cost_analysis()")
    sharded = ex.shard_batch(batch)
    key = jax.random.PRNGKey(0)
    # reuse the executor's cached jit wrapper (same donation flags, same
    # compiled program the training loop runs; no second full compile)
    lowered = ex.train_step().lower(
        model.params, model.opt_state, sharded, key
    )
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax: one dict per device
        cost = cost[0]
    return dict(cost or {})


@contextlib.contextmanager
def trace(log_dir: str):
    """XLA profiler trace (view in TensorBoard/Perfetto):

        with profiling.trace("/tmp/trace"):
            model.fit(...)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
