"""ctypes bindings to the native C++ core (libffnative.so).

The reference keeps its search-critical machinery in C++ (graph toolkit
include/flexflow/dominators.h, event simulator src/runtime/simulator.cc,
data loader python/flexflow_dataloader.cc); this package is the TPU
rebuild's equivalent native layer. The library is built on demand with the
checked-in Makefile (native/Makefile); every entry point has a pure-Python
fallback so the framework works where no C++ toolchain exists
(set FFTPU_NO_NATIVE=1 to force the fallbacks).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libffnative.so")
# wheel installs ship a prebuilt copy inside the package (setup.py
# build_py_with_native); source checkouts build via the Makefile instead
_PKG_LIB_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "libffnative.so"
)

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _sources_newer_than_lib() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    src_dir = os.path.join(_NATIVE_DIR, "src")
    for f in os.listdir(src_dir):
        if os.path.getmtime(os.path.join(src_dir, f)) > lib_mtime:
            return True
    return False


def _declare(lib: ctypes.CDLL):
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ffn_topo_sort.restype = ctypes.c_int
    lib.ffn_topo_sort.argtypes = [ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p]
    lib.ffn_imm_dominators.restype = ctypes.c_int
    lib.ffn_imm_dominators.argtypes = [ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p]
    lib.ffn_imm_post_dominators.restype = ctypes.c_int
    lib.ffn_imm_post_dominators.argtypes = [
        ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p,
    ]
    lib.ffn_transitive_reduction.restype = ctypes.c_int
    lib.ffn_transitive_reduction.argtypes = [
        ctypes.c_int32, ctypes.c_int32, i32p, i32p, u8p,
    ]
    lib.ffn_simulate.restype = ctypes.c_double
    lib.ffn_simulate.argtypes = [
        ctypes.c_int32, i32p, f64p, ctypes.c_int32, i32p, i32p,
        ctypes.c_int32, f64p, f64p,
    ]
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ffn_loader_create.restype = ctypes.c_void_p
    lib.ffn_loader_create.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), i64p,
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int64, i64p,
        ctypes.c_int32, ctypes.c_int32,
    ]
    lib.ffn_loader_num_batches.restype = ctypes.c_int64
    lib.ffn_loader_num_batches.argtypes = [ctypes.c_void_p]
    lib.ffn_loader_next.restype = ctypes.c_int64
    lib.ffn_loader_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.ffn_loader_reset.restype = None
    lib.ffn_loader_reset.argtypes = [ctypes.c_void_p, i64p]
    lib.ffn_loader_destroy.restype = None
    lib.ffn_loader_destroy.argtypes = [ctypes.c_void_p]
    lib.ffn_unity_dp.restype = ctypes.c_int
    lib.ffn_unity_dp.argtypes = [
        ctypes.c_int32, ctypes.c_int32, i32p, i32p, f64p,  # edges
        i64p, i64p, f64p, f64p, f64p, f64p,  # per-node scalars
        f64p, i32p,  # optimizer-update bytes basis + dp-scaling flags
        f64p,  # sparse touched-row sync bytes basis
        ctypes.c_double,  # optimizer traffic factor (2*state_factor - 1)
        ctypes.c_int32,  # allow sub-block concurrent-branch views
        ctypes.c_int32, i32p, i32p, i32p, f64p,  # measured-view LUT
        ctypes.c_int32, ctypes.c_int32,  # machine geometry
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_int32,  # sink
        i32p, i32p, f64p,  # out
    ]


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    if os.environ.get("FFTPU_NO_NATIVE"):
        _lib_failed = True
        return None
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if os.path.exists(_PKG_LIB_PATH):
                lib = ctypes.CDLL(_PKG_LIB_PATH)
            else:
                if _sources_newer_than_lib():
                    import sys

                    print(
                        "[flexflow_tpu] building native core (libffnative.so)…",
                        file=sys.stderr,
                        flush=True,
                    )
                    subprocess.run(
                        ["make", "-s", "-j4"],
                        cwd=_NATIVE_DIR,
                        check=True,
                        capture_output=True,
                        timeout=300,
                    )
                lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except Exception:
            _lib_failed = True
    return _lib


def _as_i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def unity_dp(
    edges,  # [(src, dst, bytes)] with node indices 0..n-1
    batch,  # per-node sample-dim sizes (<=0: single-chip only)
    chan,  # per-node channel sizes (<=0: no 2-D views)
    flops,
    bytes_moved,
    wbytes,
    bwd_mult,
    machine_nodes: int,
    chips_per_node: int,
    peak_eff: float,
    hbm_eff: float,
    ici_eff: float,
    ici_lat: float,
    sink: int,
    ubytes=None,  # optimizer-update bytes basis (defaults to wbytes)
    u_dp_scaled=None,  # per-node 1 where update traffic divides by dp
    sbytes=None,  # sparse touched-row sync bytes (all-gather over dp)
    update_factor: float = 5.0,  # 2*state_factor - 1
    allow_subblock: bool = False,  # unity.py allow_subblock_views
    measured=None,  # [(node_idx, dp, ch, cost_s)] replacing the roofline
):
    """Native Unity DP (native/src/unity_dp.cc — the reference's
    SearchHelper::graph_cost role). Returns (cost, dp[], ch[]) or None
    when the native library is unavailable or the graph exceeds 256 nodes."""
    n = len(batch)
    lib = get_lib()
    if lib is None or n > 256 or n == 0:
        return None
    esrc = _as_i32([e[0] for e in edges])
    edst = _as_i32([e[1] for e in edges])
    ebytes = np.ascontiguousarray([e[2] for e in edges], dtype=np.float64)
    b = np.ascontiguousarray(batch, dtype=np.int64)
    c = np.ascontiguousarray(chan, dtype=np.int64)
    f = np.ascontiguousarray(flops, dtype=np.float64)
    by = np.ascontiguousarray(bytes_moved, dtype=np.float64)
    w = np.ascontiguousarray(wbytes, dtype=np.float64)
    bm = np.ascontiguousarray(bwd_mult, dtype=np.float64)
    ub = np.ascontiguousarray(
        wbytes if ubytes is None else ubytes, dtype=np.float64
    )
    us = (
        np.zeros(n, dtype=np.int32)
        if u_dp_scaled is None
        else np.ascontiguousarray(u_dp_scaled, dtype=np.int32)
    )
    sb = (
        np.zeros(n, dtype=np.float64)
        if sbytes is None
        else np.ascontiguousarray(sbytes, dtype=np.float64)
    )
    out_dp = np.empty(n, dtype=np.int32)
    out_ch = np.empty(n, dtype=np.int32)
    out_cost = np.empty(1, dtype=np.float64)
    rc = lib.ffn_unity_dp(
        n, len(edges), _i32p(esrc), _i32p(edst), _f64p(ebytes),
        _i64p(b), _i64p(c), _f64p(f), _f64p(by), _f64p(w), _f64p(bm),
        _f64p(ub), _i32p(us), _f64p(sb), update_factor, int(allow_subblock),
        len(measured or []),
        _i32p(_as_i32([m[0] for m in measured or []])),
        _i32p(_as_i32([m[1] for m in measured or []])),
        _i32p(_as_i32([m[2] for m in measured or []])),
        _f64p(
            np.ascontiguousarray(
                [m[3] for m in measured or []], dtype=np.float64
            )
        ),
        machine_nodes, chips_per_node, peak_eff, hbm_eff, ici_eff, ici_lat,
        sink, _i32p(out_dp), _i32p(out_ch), _f64p(out_cost),
    )
    if rc != 0:
        return None
    return float(out_cost[0]), out_dp.tolist(), out_ch.tolist()


# -- graph algorithms ---------------------------------------------------------


def topo_sort(n: int, edges: Sequence[Tuple[int, int]]) -> Optional[List[int]]:
    """Deterministic topological order of nodes 0..n-1; None on cycle."""
    lib = get_lib()
    src = _as_i32([e[0] for e in edges])
    dst = _as_i32([e[1] for e in edges])
    if lib is not None:
        out = np.empty(n, dtype=np.int32)
        rc = lib.ffn_topo_sort(n, len(edges), _i32p(src), _i32p(dst), _i32p(out))
        return None if rc != 0 else out.tolist()
    # fallback: Kahn with sorted ready set
    indeg = [0] * n
    adj = [[] for _ in range(n)]
    for s, d in edges:
        adj[s].append(d)
        indeg[d] += 1
    import heapq

    ready = [v for v in range(n) if indeg[v] == 0]
    heapq.heapify(ready)
    order = []
    while ready:
        v = heapq.heappop(ready)
        order.append(v)
        for w in adj[v]:
            indeg[w] -= 1
            if indeg[w] == 0:
                heapq.heappush(ready, w)
    return order if len(order) == n else None


def imm_post_dominators(
    n: int, edges: Sequence[Tuple[int, int]]
) -> Optional[List[int]]:
    """ipdom[v] (or -1 when only the virtual sink post-dominates v).

    The search's find_split_node uses this to locate sequence-split
    bottlenecks (reference: dominators.h:377, substitution.cc:1984).
    """
    lib = get_lib()
    if lib is not None:
        src = _as_i32([e[0] for e in edges])
        dst = _as_i32([e[1] for e in edges])
        out = np.empty(n, dtype=np.int32)
        rc = lib.ffn_imm_post_dominators(
            n, len(edges), _i32p(src), _i32p(dst), _i32p(out)
        )
        return None if rc != 0 else out.tolist()
    return _py_imm_post_dominators(n, edges)


def _py_imm_post_dominators(n, edges):
    """Pure-Python fallback: post-dominator sets by reverse-topo dataflow,
    then ipdom = the nearest strict post-dominator."""
    order = topo_sort(n, edges)
    if order is None:
        return None
    succ = [[] for _ in range(n)]
    for s, d in edges:
        succ[s].append(d)
    full = frozenset(range(n))
    pdom = [full] * n
    for v in reversed(order):
        if not succ[v]:
            pdom[v] = frozenset([v])
        else:
            inter = frozenset.intersection(*[pdom[s] for s in succ[v]])
            pdom[v] = inter | {v}
    index = {v: i for i, v in enumerate(order)}
    out = []
    for v in range(n):
        strict = [d for d in pdom[v] if d != v]
        # nearest = the one earliest in topo order among strict post-doms
        out.append(min(strict, key=lambda d: index[d]) if strict else -1)
    return out


def transitive_reduction(
    n: int, edges: Sequence[Tuple[int, int]]
) -> Optional[List[bool]]:
    """keep[i] per edge; False when implied by a longer path."""
    lib = get_lib()
    src = [e[0] for e in edges]
    dst = [e[1] for e in edges]
    if lib is not None:
        out = np.empty(len(edges), dtype=np.uint8)
        rc = lib.ffn_transitive_reduction(
            n, len(edges), _i32p(_as_i32(src)), _i32p(_as_i32(dst)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return None if rc != 0 else [bool(x) for x in out]
    adj = [[] for _ in range(n)]
    for s, d in edges:
        adj[s].append(d)
    keep = []
    for s, d in edges:
        seen = set()
        stack = [w for w in adj[s] if w != d]
        found = False
        while stack:
            v = stack.pop()
            if v == d:
                found = True
                break
            for w in adj[v]:
                if w not in seen:
                    seen.add(w)
                    stack.append(w)
        keep.append(not found)
    return keep


# -- event-driven simulator ---------------------------------------------------


def simulate(
    resource_of: Sequence[int],
    duration: Sequence[float],
    edges: Sequence[Tuple[int, int]],
    num_resources: int,
) -> Optional[Tuple[float, np.ndarray]]:
    """Replay a task DAG; returns (makespan, per-resource busy time).

    Native path is ffn_simulate (reference: simulate_runtime,
    simulator.cc:810-1240); fallback is an equivalent Python event loop.
    """
    n = len(resource_of)
    lib = get_lib()
    if lib is not None:
        res = _as_i32(resource_of)
        dur = np.ascontiguousarray(duration, dtype=np.float64)
        src = _as_i32([e[0] for e in edges])
        dst = _as_i32([e[1] for e in edges])
        busy = np.zeros(num_resources, dtype=np.float64)
        ms = lib.ffn_simulate(
            n, _i32p(res), dur.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(edges), _i32p(src), _i32p(dst), num_resources,
            busy.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), None,
        )
        return None if ms < 0 else (float(ms), busy)
    return _py_simulate(resource_of, duration, edges, num_resources)


def _py_simulate(resource_of, duration, edges, num_resources):
    import heapq

    n = len(resource_of)
    out_edges = [[] for _ in range(n)]
    unmet = [0] * n
    for s, d in edges:
        out_edges[s].append(d)
        unmet[d] += 1
    ready = [[] for _ in range(num_resources)]  # heaps of (ready_t, task)
    running = [False] * num_resources
    busy = np.zeros(num_resources)
    done_heap = []
    completed = 0
    makespan = 0.0

    def try_start(r, now):
        if running[r] or not ready[r]:
            return
        _, t = heapq.heappop(ready[r])
        end = now + duration[t]
        running[r] = True
        busy[r] += duration[t]
        heapq.heappush(done_heap, (end, t))

    for i in range(n):
        if unmet[i] == 0:
            heapq.heappush(ready[resource_of[i]], (0.0, i))
    for r in range(num_resources):
        try_start(r, 0.0)
    while done_heap:
        now, t = heapq.heappop(done_heap)
        makespan = max(makespan, now)
        completed += 1
        r = resource_of[t]
        running[r] = False
        for s in out_edges[t]:
            unmet[s] -= 1
            if unmet[s] == 0:
                heapq.heappush(ready[resource_of[s]], (now, s))
        try_start(r, now)
        for s in out_edges[t]:
            rs = resource_of[s]
            if not running[rs]:
                try_start(rs, now)
    if completed != n:
        return None
    return makespan, busy


# -- data loader --------------------------------------------------------------


class NativeLoader:
    """Background-threaded shuffle/batch/prefetch loader (reference:
    SingleDataLoader, python/flexflow_dataloader.h:34). Falls back to
    synchronous numpy batching without the native library.

    The epoch permutation is always drawn from numpy's seeded RNG here in
    Python and handed to the C++ side, so the batch stream for a given seed
    is identical whether or not the native library loaded."""

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        prefetch_depth: int = 2,
    ):
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        n = self.arrays[0].shape[0]
        for a in self.arrays:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the sample dimension")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._handle = None
        self._lib = get_lib()
        self._perm = self._make_perm(seed)
        if self._lib is not None:
            ptrs = (ctypes.c_void_p * len(self.arrays))(
                *[a.ctypes.data_as(ctypes.c_void_p).value for a in self.arrays]
            )
            row_bytes = (ctypes.c_int64 * len(self.arrays))(
                *[a.nbytes // n for a in self.arrays]
            )
            self._handle = self._lib.ffn_loader_create(
                ptrs, row_bytes, len(self.arrays), n, batch_size,
                self._perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                1 if drop_last else 0, prefetch_depth,
            )
        self._pos = 0

    def _make_perm(self, seed) -> np.ndarray:
        idx = np.arange(self.arrays[0].shape[0], dtype=np.int64)
        if self.shuffle:
            np.random.RandomState(seed).shuffle(idx)
        return np.ascontiguousarray(idx)

    @property
    def num_batches(self) -> int:
        n = self.arrays[0].shape[0]
        if self._handle is not None:
            return int(self._lib.ffn_loader_num_batches(self._handle))
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def next_batch(self) -> Optional[List[np.ndarray]]:
        """Returns per-array [batch_size, ...] copies, or None at epoch end."""
        if self._handle is not None:
            ptrs = (ctypes.c_void_p * len(self.arrays))()
            idx = self._lib.ffn_loader_next(self._handle, ptrs)
            if idx < 0:
                return None
            out = []
            for a, p in zip(self.arrays, ptrs):
                shape = (self.batch_size,) + a.shape[1:]
                buf = np.ctypeslib.as_array(
                    ctypes.cast(p, ctypes.POINTER(ctypes.c_uint8)),
                    shape=(int(np.prod(shape)) * a.itemsize,),
                )
                out.append(buf.view(a.dtype).reshape(shape).copy())
            return out
        if self._pos >= self.num_batches:
            return None
        b = self._pos
        self._pos += 1
        rows = self._perm[b * self.batch_size : (b + 1) * self.batch_size]
        if len(rows) < self.batch_size:  # pad short final batch
            rows = np.concatenate(
                [rows, np.repeat(rows[:1], self.batch_size - len(rows))]
            )
        return [a[rows] for a in self.arrays]

    def reset(self, seed: Optional[int] = None):
        seed = self.seed if seed is None else seed
        self.reset_perm(self._make_perm(seed))

    def reset_perm(self, perm: np.ndarray):
        """New epoch with an explicit sample order (len == num_samples)."""
        self._perm = np.ascontiguousarray(perm, dtype=np.int64)
        self._pos = 0
        if self._handle is not None:
            self._lib.ffn_loader_reset(
                self._handle,
                self._perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )

    def __del__(self):
        if getattr(self, "_handle", None) is not None and self._lib is not None:
            self._lib.ffn_loader_destroy(self._handle)
            self._handle = None


def available() -> bool:
    return get_lib() is not None
