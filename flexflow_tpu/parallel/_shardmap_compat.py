"""shard_map across JAX versions (one shim, shared by every caller).

jax >= 0.8 exposes `jax.shard_map` with `check_vma`; older versions have
`jax.experimental.shard_map.shard_map` with `check_rep`. pyproject pins
no jax floor, so the compat choice lives here once (pipeline.py and
submesh.py both consume it)."""

from __future__ import annotations

import inspect

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_CHECK_KW = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(_shard_map).parameters
    else {"check_rep": False}
)


def shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking off (our inner functions use
    psum/all_gather collectives the checker cannot always see through)."""
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **_CHECK_KW
    )
