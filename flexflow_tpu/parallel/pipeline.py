"""Pipeline parallelism: GPipe over a `pipe` mesh axis.

The reference DECLARES pipeline parallelism but never implements it
(reference: OP_PIPELINE enum ffconst.h:151 + PIPELINE_*_TASK_ID
model.h:186-188 with no operator in src/parallel_ops/ — SURVEY §2.3);
this module is the TPU-native implementation that closes the gap.

Design (the idiomatic SPMD pipeline, per the public scaling-book recipe):
each device along the `pipe` mesh axis owns ONE stage's weights (the
stacked stage axis of the parameter pytree is sharded over `pipe`);
`shard_map` runs the same program on every stage; microbatches stream
through a `lax.scan` time loop; activations hop stage→stage via
`lax.ppermute`. One jitted function, XLA collectives over ICI, fully
differentiable (grads flow through ppermute), so the SAME train-step
machinery (jax.value_and_grad + optimizer) works unchanged.

Bubble fraction is the GPipe (S-1)/(T) with T = num_microbatches + S - 1
schedule steps; raise num_microbatches to amortize.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _shift_right(x, axis_name: str, num_stages: int):
    """ppermute stage i → i+1 (stage 0 receives zeros from nowhere)."""
    perm = [(i, i + 1) for i in range(num_stages - 1)]
    return jax.lax.ppermute(x, axis_name, perm)


def gpipe(
    block_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    *,
    axis_name: str = "pipe",
    num_microbatches: int,
):
    """Run a homogeneous-stage pipeline INSIDE shard_map.

    block_fn(params_leaf_slice, activations) -> activations — one stage's
    computation; must map activations to activations of the same shape.
    stage_params: pytree whose leaves carry THIS stage's slice (shard_map
    has already split the stacked stage axis).
    x: [batch, ...] the microbatch source (meaningful on stage 0).

    Returns [batch, ...] outputs (meaningful on the LAST stage; other
    stages return zeros — psum over `pipe` outside if a replicated result
    is wanted).
    """
    num_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    batch = x.shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by num_microbatches={num_microbatches}"
        )
    mb = batch // num_microbatches
    xs = x.reshape((num_microbatches, mb) + x.shape[1:])
    # pad the microbatch stream with zeros for the drain phase
    pad = jnp.zeros((num_stages - 1, mb) + x.shape[1:], x.dtype)
    stream = jnp.concatenate([xs, pad], axis=0)

    def step(carry, x_t):
        recv = carry
        # stage 0 consumes the next microbatch; others consume the hop
        inp = jnp.where(stage == 0, x_t, recv)
        out = block_fn(stage_params, inp)
        send = _shift_right(out, axis_name, num_stages)
        # emit this step's output (only the last stage's is real)
        return send, out

    # the carry dtype must match the BLOCK's output dtype, not the input's:
    # under mixed precision blocks emit bf16 activations (mm_out_dtype)
    # while the pipeline entry is f32
    out_sd = jax.eval_shape(block_fn, stage_params, xs[0])
    _, outs = jax.lax.scan(
        step, jnp.zeros(out_sd.shape, out_sd.dtype), stream
    )
    # the last stage produced microbatch m at step m + (S-1)
    tail = outs[num_stages - 1 :]
    y = tail.reshape((batch,) + tail.shape[2:])
    is_last = (stage == num_stages - 1).astype(y.dtype)
    return y * is_last


def pipeline_apply(
    mesh: Mesh,
    block_fn: Callable,
    stacked_params,
    x,
    *,
    axis_name: str = "pipe",
    num_microbatches: int = 4,
    data_axis: str | None = None,
    stage_leading_axis: bool = False,
):
    """jit-able entry: shard_map the GPipe loop over `mesh`.

    stacked_params: pytree with a leading stage axis on every leaf
    (stage s's weights at index s), sharded over `axis_name`.
    x: global [batch, ...] input; optionally data-parallel over `data_axis`
    (pipeline × data two-axis meshes compose).

    stage_leading_axis: when each stage runs SEVERAL model blocks (leaves
    stacked [num_stages * blocks_per_stage, ...]), pass True — block_fn
    then receives its slice with the per-stage leading axis intact
    ([blocks_per_stage, ...]) and is responsible for looping over it.

    Returns the global [batch, ...] output, replicated over `axis_name`
    (psum of the last stage's emission).
    """
    def inner(params, xin):
        if stage_leading_axis:
            local = params
        else:
            local = jax.tree_util.tree_map(lambda p: p[0], params)
        y = gpipe(
            block_fn,
            local,
            xin,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
        )
        return jax.lax.psum(y, axis_name)

    p_spec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis_name), stacked_params
    )
    x_spec = PartitionSpec(data_axis) if data_axis else PartitionSpec()
    from flexflow_tpu.parallel._shardmap_compat import shard_map_unchecked

    mapped = shard_map_unchecked(
        inner,
        mesh,
        in_specs=(p_spec, x_spec),
        out_specs=x_spec,
    )
    return mapped(stacked_params, x)


def pipeline_bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """GPipe bubble overhead: idle step fraction of the schedule."""
    steps = num_microbatches + num_stages - 1
    return (num_stages - 1) / steps
