"""Parallelization strategies: how an un-annotated PCG gets its parallel dims.

A Strategy bundles the global MeshConfig with the per-tensor degree
annotations. The data-parallel strategy replicates the reference's
`--only-data-parallel` mode (reference: graph.cc:1588-1613 — a 1-D view over
all devices partitioning the sample dim). Searched strategies (Unity DP /
MCMC, flexflow_tpu.search) produce per-op annotations that `apply` writes
into the graph before shape propagation.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Tuple

from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.runtime.executor import MeshConfig


@dataclasses.dataclass
class Strategy:
    mesh_config: MeshConfig
    # callable mutating the graph's source annotations / inserting parallel ops
    _apply: Optional[Callable[[PCGGraph], None]] = None
    name: str = "custom"
    # set on dp×pp strategies: compile() routes the repeated trunk through
    # the GPipe executor (runtime.pipeline_executor.PipelinedExecutor)
    pipeline: Optional[object] = None  # runtime.pipeline_executor.PipelineSpec

    def apply(self, graph: PCGGraph):
        if self._apply is not None:
            self._apply(graph)


def annotate_input_batch(graph: PCGGraph, dp: int, strict: bool = False):
    """Shard every source INPUT's batch (outermost) dim `dp` ways — the one
    place this annotation is written (data-parallel, searched, and imported
    strategies all route here). strict=True raises on a non-dividing batch;
    otherwise the caller is expected to have clamped dp already."""
    if dp <= 1:
        return
    for node in graph.nodes.values():
        if node.op_type == OperatorType.INPUT and not node.inputs:
            shape: ParallelTensorShape = node.params["shape"]
            if shape.dims[0].size % dp != 0:
                if strict:
                    raise ValueError(
                        f"input '{node.name}' batch {shape.dims[0].size} "
                        f"not divisible by dp={dp}"
                    )
                continue
            node.params["shape"] = shape.data_parallel(dp)
            node.output_shapes = (node.params["shape"],)


def effective_dp_degree(graph: PCGGraph, num_devices: int) -> int:
    """Largest degree <= num_devices dividing every input's batch dim.
    The mesh is sized to this degree — a PartitionSpec must shard a dim
    exactly axis-size ways, so degree and mesh axis cannot disagree."""
    batches = [
        n.params["shape"].dims[0].size
        for n in graph.nodes.values()
        if n.op_type == OperatorType.INPUT and not n.inputs
    ]
    if not batches:
        return 1
    for d in range(min(num_devices, min(batches)), 0, -1):
        if all(b % d == 0 for b in batches):
            return d
    return 1


def data_parallel_strategy(num_devices: int, graph: PCGGraph = None) -> Strategy:
    """Partition every input's sample (outermost) dim over the data axis
    (reference: --only-data-parallel, graph.cc:1588-1613)."""
    dp = (
        effective_dp_degree(graph, num_devices)
        if graph is not None
        else num_devices
    )

    def apply(g: PCGGraph):
        annotate_input_batch(g, effective_dp_degree(g, dp))

    return Strategy(
        MeshConfig.data_parallel(max(dp, 1)), apply, name="data-parallel"
    )


def _second_axis_strategy(
    axis_name: str, dp: int, degree: int, axis: int, eligible, name: str
) -> Strategy:
    """Shared builder for (data × <axis>) strategies: batch on "data",
    one more input dim (seq / spatial) on the second mesh axis when the
    eligibility predicate admits it."""

    def apply(g: PCGGraph):
        annotate_input_batch(g, dp)
        for node in g.nodes.values():
            if node.op_type == OperatorType.INPUT and not node.inputs:
                shape: ParallelTensorShape = node.params["shape"]
                if (
                    degree > 1
                    and eligible(shape)
                    and shape.dims[axis].size % degree == 0
                ):
                    shape = shape.with_degree(axis, degree, 1)
                node.params["shape"] = shape
                node.output_shapes = (shape,)

    return Strategy(
        MeshConfig(("data", axis_name), (max(dp, 1), max(degree, 1))),
        apply,
        name=name,
    )


def sequence_parallel_strategy(
    dp: int, sp: int, graph: PCGGraph = None, seq_axis: int = 1,
    seq_mode: str = "ring",
) -> Strategy:
    """dp × sp mesh: inputs' batch dim on the "data" axis and sequence dim on
    the "seq" axis. Attention under the partitioned sequence dim runs the
    ring-attention path (ops/pallas/ring_attention.py) or, with
    seq_mode="ulysses", the all-to-all seq->heads reshard — whichever the
    cost model picked (the long-context capability the reference lacks,
    SURVEY §5)."""
    if seq_mode not in ("ring", "ulysses"):
        raise ValueError(f"seq_mode must be ring|ulysses, got {seq_mode!r}")
    base = _second_axis_strategy(
        "seq",
        dp,
        sp,
        seq_axis,
        # a real sequence is rank-3 [batch, seq, features]; rank-4 images
        # belong to the SPATIAL family (--enable-attribute-parallel), not
        # here — without this split the search's "seq" candidates quietly
        # shard image H dims and the two families double-count
        lambda shape: shape.ndim == seq_axis + 2,
        f"dp{dp}xsp{sp}" + ("-ulysses" if seq_mode == "ulysses" else ""),
    )
    if seq_mode == "ring":
        return base

    base_apply = base.apply

    def apply(g: PCGGraph):
        base_apply(g)
        for node in g.nodes.values():
            if not ulysses_eligible(node, sp):
                continue
            node.params["seq_parallel"] = "ulysses"

    return Strategy(base.mesh_config, apply, name=base.name)


def ulysses_eligible(node, sp: int) -> bool:
    """Whether a node can take the Ulysses seq->heads reshard: an MHA
    whose heads divide sp, without attention-prob dropout (the reshard
    path has no dropout support — ops/attention.py raises), and whose
    seq_parallel the user left on auto (an explicit ring/none choice is
    never clobbered)."""
    if node.op_type != OperatorType.MULTIHEAD_ATTENTION:
        return False
    heads = int(node.params.get("num_heads", 0))
    return (
        heads > 0
        and heads % sp == 0
        and float(node.params.get("dropout", 0.0)) == 0.0
        and node.params.get("seq_parallel", "auto") == "auto"
    )


def spatial_parallel_strategy(
    dp: int, hp: int, graph: PCGGraph = None, spatial_axis: int = 1
) -> Strategy:
    """Attribute/spatial parallelism (reference: --enable-attribute-parallel,
    model.cc:3602 — partition non-sample activation dims): image inputs'
    H dim shards over a "spatial" mesh axis. Convolutions under a sharded
    spatial dim are handled by GSPMD's windowed-op halo exchange — the
    TPU-native replacement for the reference's Legion-partition overlap."""
    return _second_axis_strategy(
        "spatial",
        dp,
        hp,
        spatial_axis,
        lambda shape: shape.ndim == 4,  # NHWC rank-4 images only
        f"dp{dp}xhp{hp}",
    )


def pipeline_strategy(
    graph: PCGGraph,
    dp: int,
    pp: int,
    structure=None,
    num_microbatches: int = 4,
    schedule: str = "gpipe",
    name_prefix: str = "pipeline",
) -> Strategy:
    """dp × pp strategy: batch on "data", the repeated trunk GPipe'd over
    the "pipe" axis with stage weights SHARDED over it (the reference
    declares OP_PIPELINE but never implements it, ffconst.h:151 — this
    closes that gap). `structure` is a search.blocks.BlockStructure;
    detected here when omitted. schedule: "gpipe" | "1f1b"
    (runtime.pipeline_executor.PipelineSpec)."""
    from flexflow_tpu.runtime.pipeline_executor import PipelineSpec
    from flexflow_tpu.search.blocks import find_block_structure

    if structure is None:
        structure = find_block_structure(graph)
    if structure is None:
        raise ValueError("graph has no repeated-block trunk to pipeline")
    if structure.num_blocks % pp != 0:
        raise ValueError(
            f"{structure.num_blocks} blocks not divisible by pp={pp}"
        )
    dp = effective_dp_degree(graph, max(1, dp))

    def apply(g: PCGGraph):
        annotate_input_batch(g, dp)

    mesh = (
        MeshConfig(("data", "pipe"), (dp, pp))
        if dp > 1
        else MeshConfig(("pipe",), (pp,))
    )
    return Strategy(
        mesh,
        apply,
        name=(
            f"{name_prefix}: mesh(data={dp}, pipe={pp}), "
            f"{structure.num_blocks} blocks"
            + (f", {schedule}" if schedule != "gpipe" else "")
        ),
        pipeline=PipelineSpec(pp, num_microbatches, structure, schedule),
    )


def site_strategy(
    graph: PCGGraph,
    num_devices: int,
    tp: int,
    sites,
    name_prefix: str = "searched",
) -> Strategy:
    """Shared lowering for searched strategies: a (data × model) mesh plus
    TP rewrite sites. dp is clamped to the largest feasible batch divisor
    (an infeasible dp would make _annotate_data_parallel raise at compile)."""
    tp = max(1, tp)
    dp = effective_dp_degree(graph, max(1, num_devices // tp))

    def apply(g: PCGGraph):
        annotate_input_batch(g, dp)
        for site in sites:
            site.apply(g, tp, 1)  # model axis = 1
        from flexflow_tpu.search.peephole import sink_combines

        sink_combines(g)  # keep the lowered graph == the costed candidate

    mesh = (
        MeshConfig(("data", "model"), (dp, tp))
        if tp > 1
        else MeshConfig(("data",), (max(dp, 1),))
    )
    return Strategy(
        mesh,
        apply,
        name=(
            f"{name_prefix}: mesh(data={dp}, model={tp}), "
            f"{len(list(sites))} TP sites"
        ),
    )


def mixed_site_strategy(
    graph: PCGGraph,
    num_devices: int,
    tp: int,
    sites,
    name_prefix: str = "searched",
) -> Strategy:
    """Per-op heterogeneous lowering (reference: per-op MachineViews in
    SearchHelper::graph_cost, graph.cc:1346-1431 — e.g. DLRM shards
    embedding tables model-parallel while the MLPs stay data-parallel).

    One (data × model) mesh, two sharding regimes on it: ops OUTSIDE the
    TP sites shard their batch over BOTH axes (full-width data parallelism
    via PartitionSpec spans, ParallelTensorShape.partition_spec), while
    each site shards channels/heads/columns on the model axis. Sites are
    bracketed by batch-Combine (full→data-axis-only) on entry and
    batch-Repartition (back to full width) on exit; GSPMD lowers the
    brackets to the boundary collectives. Falls back to the uniform
    `site_strategy` when the full-width batch shard is infeasible or a
    site kind has no batch-dim-0 bracket semantics."""
    from flexflow_tpu.search.rewrites import _insert_after, _insert_before

    sites = list(sites)
    tp = max(1, tp)
    dp = effective_dp_degree(graph, max(1, num_devices // tp))
    full = dp * tp
    bracketable = {
        "linear_chain", "single_linear", "attention", "embedding",
        "conv_channel",
    }
    if (
        tp == 1
        or effective_dp_degree(graph, full) != full
        or any(s.kind not in bracketable for s in sites)
    ):
        return site_strategy(graph, num_devices, tp, sites, name_prefix)

    def apply(g: PCGGraph):
        annotate_input_batch(g, full)
        for site in sites:
            head, tail = site.guids[0], site.guids[-1]
            hnode = g.nodes[head]
            for ref in dict.fromkeys(hnode.inputs):
                _insert_before(
                    g,
                    head,
                    ref,
                    OperatorType.COMBINE,
                    f"{hnode.name}.batch_combine",
                    {"axis": 0, "degree": tp},
                )
            _insert_after(
                g,
                tail,
                OperatorType.REPARTITION,
                f"{g.nodes[tail].name}.batch_repartition",
                {"axis": 0, "degree": tp, "parallel_idx": 0},
            )
            site.apply(g, tp, 1)
        from flexflow_tpu.search.peephole import sink_combines

        sink_combines(g)

    return Strategy(
        MeshConfig(("data", "model"), (dp, tp)),
        apply,
        name=(
            f"{name_prefix}: mixed mesh(data={dp}, model={tp}), "
            f"{len(sites)} TP sites, full-width dp={full} outside them"
        ),
    )


def choose_strategy(model, num_devices: int) -> Strategy:
    """Strategy selection at compile() (reference: model.cc:2789 →
    graph_optimize_task, graph.cc:1545-1613): data-parallel unless a search
    budget asks for the Unity-style search."""
    cfg = model.config
    if cfg.import_strategy_file:
        from flexflow_tpu.search.strategy_io import load_strategy

        return load_strategy(cfg.import_strategy_file, model.graph, num_devices)
    if cfg.only_data_parallel or cfg.search_budget <= 0:
        if (
            cfg.enable_parameter_parallel
            and not cfg.only_data_parallel
            and num_devices > 1
        ):
            # --enable-parameter-parallel without a search budget: shard
            # the embedding tables over the devices deterministically
            # (the reference's DLRM usage — embedding.cc weight sharding
            # driven by the flag + strategy files, no search needed) and
            # keep everything else full-width data-parallel
            from flexflow_tpu.search.rewrites import (
                EmbeddingSite,
                find_tp_sites,
            )

            sites = [
                s
                for s in find_tp_sites(model.graph)
                if isinstance(s, EmbeddingSite)
                and s.divisible_by(model.graph, num_devices)
            ]
            if sites:
                s = mixed_site_strategy(
                    model.graph,
                    num_devices,
                    num_devices,
                    sites,
                    name_prefix="parameter-parallel",
                )
                if "mixed" in s.name:
                    return s
        return data_parallel_strategy(num_devices, model.graph)
    from flexflow_tpu.search.auto import search_strategy

    return search_strategy(model, num_devices)


def export_strategy(strategy: Strategy, path: str):
    from flexflow_tpu.search.strategy_io import save_strategy

    save_strategy(strategy, path)
