"""Concurrent branch execution on disjoint device sub-blocks.

The reference executes per-op MachineViews: Unity's nonsequence split
places parallel branches on vertical/horizontal resource sub-blocks and
runs them CONCURRENTLY (reference: graph.cc:252-306 resource splits +
mapper.cc:377-481 per-point placement — Legion is MPMD, every task can
target its own device set). Under one jitted XLA program that freedom
does not exist: GSPMD is SPMD, one program on every device, and two
dataflow-independent ops each sharded over the full mesh execute
sequentially.

This module provides the TPU-native middle ground:
`concurrent_branches` runs K branch functions on K disjoint sub-blocks
of a mesh axis inside ONE jit program, via shard_map + lax.switch on the
block index — each device group executes only its branch's computation,
so the branches genuinely overlap in time. It is the executable
counterpart of the unity DP's sub-block costing
(UnitySearch allow_subblock_views).

SPMD restrictions (vs the reference's full MPMD generality, documented
here once):
  * every branch must return outputs with the SAME shapes/dtypes
    (lax.switch unifies the program across groups);
  * inputs are broadcast to every group (each group reads what it
    needs);
  * the branch axis size must equal the number of branches.

Differentiable end to end (switch + psum have transposes), so it can sit
inside a train step.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _stack_branch_params(mesh: Mesh, axis_name: str, branch_params):
    """Stack per-branch parameter pytrees on a leading branch axis,
    sharded over `axis_name` — each block's devices hold ONLY their
    branch's slice (the reference's per-op weight placement). Branches
    must share a parameter structure (like unity's template blocks)."""
    flat = [jax.tree_util.tree_flatten(p) for p in branch_params]
    treedef = flat[0][1]
    for _, td in flat[1:]:
        if td != treedef:
            raise ValueError(
                "branches must share a parameter structure "
                f"({td} != {treedef})"
            )
    stacked = [
        jnp.stack([leaves[i] for leaves, _ in flat])
        for i in range(len(flat[0][0]))
    ]
    stacked = [
        jax.device_put(
            s,
            NamedSharding(
                mesh,
                PartitionSpec(axis_name, *([None] * (s.ndim - 1))),
            ),
        )
        for s in stacked
    ]
    return stacked, treedef


def _run_block_mapped(mesh, axis_name, body, stacked, x):
    """Shared shard_map harness for the block axis: `body(local_leaves,
    xin)` runs with this block's parameter slices and the broadcast
    input; outputs gather to a replicated [k, ...] stack."""
    from flexflow_tpu.parallel._shardmap_compat import shard_map_unchecked

    def inner(params_slices, xin):
        out = body([p[0] for p in params_slices], xin)
        return jax.tree_util.tree_map(
            lambda o: jax.lax.all_gather(o, axis_name), out
        )

    specs_p = [
        PartitionSpec(axis_name, *([None] * (s.ndim - 1))) for s in stacked
    ]
    fn = shard_map_unchecked(
        inner,
        mesh,
        in_specs=(tuple(specs_p), PartitionSpec()),
        out_specs=PartitionSpec(),
    )
    return fn(tuple(stacked), x)


def concurrent_template_branches(
    mesh: Mesh,
    axis_name: str,
    template_fn: Callable,
    branch_params: Sequence,
    x,
):
    """Template-identical special case of concurrent_branches: every
    branch runs the SAME function with its own parameters (unity's
    nonsequence splits over repeated structures — Inception towers,
    per-expert stacks). No lax.switch needed: one program, per-block
    weights, which XLA can overlap freely. Returns the [k, ...] stacked
    outputs (branch i at index i, replicated)."""
    k = len(branch_params)
    if mesh.shape[axis_name] != k:
        raise ValueError(
            f"axis {axis_name!r} has size {mesh.shape[axis_name]}, "
            f"need one block per branch ({k})"
        )
    stacked, treedef = _stack_branch_params(mesh, axis_name, branch_params)

    def body(local_leaves, xin):
        return template_fn(
            jax.tree_util.tree_unflatten(treedef, local_leaves), xin
        )

    return _run_block_mapped(mesh, axis_name, body, stacked, x)


def concurrent_branches(
    mesh: Mesh,
    axis_name: str,
    branch_fns: Sequence[Callable],
    branch_params: Sequence,
    x,
):
    """Run branch_fns[i](branch_params[i], x) on sub-block i of
    `axis_name`, concurrently, inside one jitted program.

    branch_params: one pytree per branch; leaves are stacked on a new
    leading axis internally (sharded over `axis_name`), so each group's
    devices hold only their branch's parameters — the per-op weight
    placement of the reference's MachineViews.

    Returns the list of branch outputs (each replicated over the mesh).
    """
    k = len(branch_fns)
    if mesh.shape[axis_name] != k:
        raise ValueError(
            f"axis {axis_name!r} has size {mesh.shape[axis_name]}, "
            f"need one block per branch ({k})"
        )
    stacked, treedef = _stack_branch_params(mesh, axis_name, branch_params)

    def body(local_leaves, xin):
        idx = jax.lax.axis_index(axis_name)

        def make_branch(i):
            def run(args):
                local_p, xb = args
                return branch_fns[i](
                    jax.tree_util.tree_unflatten(treedef, local_p), xb
                )

            return run

        return jax.lax.switch(
            idx, [make_branch(i) for i in range(k)], (local_leaves, xin)
        )

    stacked_out = _run_block_mapped(mesh, axis_name, body, stacked, x)
    return [
        jax.tree_util.tree_map(lambda o: o[i], stacked_out)
        for i in range(k)
    ]
