"""Parallel operators: Repartition, Combine, Replicate, Reduction, FusedParallel.

Re-design of the reference's parallel-op layer (reference: src/parallel_ops/,
include/flexflow/parallel_ops/parallel_op.h:17; SURVEY §2.3). These ops change
only the parallel layout of a tensor, not its math:

  | op          | reference semantics (fwd)         | TPU lowering            |
  |-------------|-----------------------------------|-------------------------|
  | Repartition | split a dim `degree×` more ways   | sharding constraint     |
  | Combine     | merge a dim's partitions          | sharding constraint     |
  | Replicate   | add replica dim (broadcast)       | sharding constraint     |
  | Reduction   | sum over replica dim              | sharding constraint     |

In the reference, data movement happens through Legion partitions read by the
op's index tasks (reference: combine.cc:135-176); grads of Replicate are
summed (reference: replicate_kernels.cu:35-57). Here every parallel op is an
*identity on the global logical array* whose output ParallelTensorShape
carries the new layout; the executor emits
`jax.lax.with_sharding_constraint` from that shape and GSPMD inserts the
matching collectives (all-to-all / all-gather / psum / reduce-scatter) over
ICI — including the transposed ones in the backward pass, which XLA derives
automatically (Replicate's grad-psum falls out of differentiation).

One real semantic note: a "partial-sums" replica dim (produced by a Linear
whose contraction dim is partitioned) does not exist at the logical-array
level — jnp.matmul expresses the full contraction and GSPMD materializes the
partial sums + psum when the weight is sharded on the contraction dim. The
Reduction op therefore marks *where* the psum lands, which the cost model
charges for, but lowers to a layout constraint only.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.ops.registry import register_op


def _identity_lower(params):
    def fn(ins, ws, ctx):
        return [ins[0]]

    return fn


# ---------------------------------------------------------------------------
# Repartition (reference: src/parallel_ops/partition.cc)
# ---------------------------------------------------------------------------


def _infer_repartition(input_shapes, params):
    (x,) = input_shapes
    axis = params["axis"]
    degree = params["degree"]
    parallel_idx = params.get("parallel_idx", -1)
    d = x.dims[axis]
    if d.is_replica_dim:
        raise ValueError("repartition: use Replicate for replica dims")
    new_degree = d.degree * degree
    if d.size % new_degree != 0:
        raise ValueError(
            f"repartition: degree {new_degree} does not divide size {d.size}"
        )
    out = x.with_dim(axis, ParallelDim(d.size, new_degree, parallel_idx))
    return (out,), ()


register_op(OperatorType.REPARTITION, _infer_repartition, _identity_lower)


# ---------------------------------------------------------------------------
# Combine (reference: src/parallel_ops/combine.cc:88 degree /= combine_degree)
# ---------------------------------------------------------------------------


def _infer_combine(input_shapes, params):
    (x,) = input_shapes
    axis = params["axis"]
    degree = params["degree"]
    d = x.dims[axis]
    if d.degree % degree != 0:
        raise ValueError(
            f"combine: combine degree {degree} does not divide dim degree {d.degree}"
        )
    new_degree = d.degree // degree
    pidx = d.parallel_idx if new_degree > 1 else params.get("parallel_idx", -1)
    if new_degree == 1:
        pidx = -1
    out = x.with_dim(axis, ParallelDim(d.size, new_degree, pidx))
    return (out,), ()


register_op(OperatorType.COMBINE, _infer_combine, _identity_lower)


# ---------------------------------------------------------------------------
# Replicate (reference: src/parallel_ops/replicate.cc)
# ---------------------------------------------------------------------------


def _infer_replicate(input_shapes, params):
    (x,) = input_shapes
    degree = params["degree"]
    parallel_idx = params.get("parallel_idx", -1)
    out = x.append_replica_dim(degree, parallel_idx)
    return (out,), ()


register_op(OperatorType.REPLICATE, _infer_replicate, _identity_lower)


# ---------------------------------------------------------------------------
# Reduction (reference: src/parallel_ops/reduction.cc)
# ---------------------------------------------------------------------------


def _infer_reduction(input_shapes, params):
    (x,) = input_shapes
    degree = params["degree"]
    rep_idx = None
    for i, d in enumerate(x.dims):
        if d.is_replica_dim:
            rep_idx = i
            break
    if rep_idx is None:
        raise ValueError("reduction: input has no replica dim")
    if x.dims[rep_idx].degree != degree:
        raise ValueError(
            f"reduction: degree {degree} != replica degree {x.dims[rep_idx].degree}"
        )
    out = ParallelTensorShape(
        x.dims[:rep_idx] + x.dims[rep_idx + 1 :], x.dtype
    )
    return (out,), ()


register_op(OperatorType.REDUCTION, _infer_reduction, _identity_lower)


# ---------------------------------------------------------------------------
# AllToAll (TPU-native addition: Ulysses-style sequence<->head reshard)
# ---------------------------------------------------------------------------


def _infer_alltoall(input_shapes, params):
    """Move partitioning from src_axis to dst_axis in one collective."""
    (x,) = input_shapes
    src, dst = params["src_axis"], params["dst_axis"]
    d_src = x.dims[src]
    if d_src.degree == 1:
        raise ValueError("alltoall: src axis not partitioned")
    degree, pidx = d_src.degree, d_src.parallel_idx
    d_dst = x.dims[dst]
    if d_dst.degree != 1:
        raise ValueError("alltoall: dst axis already partitioned")
    out = x.with_dim(src, ParallelDim(d_src.size)).with_dim(
        dst, ParallelDim(d_dst.size, degree, pidx)
    )
    return (out,), ()


register_op(OperatorType.ALLTOALL, _infer_alltoall, _identity_lower)


# ---------------------------------------------------------------------------
# FusedParallelOp (reference: src/parallel_ops/fused_parallel_op.cc)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ParallelOpInfo:
    """One step of a fused parallel chain
    (reference: parallel_op.h ParallelOpInfo)."""

    op_type: OperatorType
    axis: int
    degree: int
    parallel_idx: int = -1


def _infer_fused_parallel(input_shapes, params):
    shape = input_shapes[0]
    for info in params["chain"]:
        sub = {
            "axis": info.axis,
            "degree": info.degree,
            "parallel_idx": info.parallel_idx,
        }
        if info.op_type == OperatorType.REPARTITION:
            (shape,), _ = _infer_repartition([shape], sub)
        elif info.op_type == OperatorType.COMBINE:
            (shape,), _ = _infer_combine([shape], sub)
        elif info.op_type == OperatorType.REPLICATE:
            (shape,), _ = _infer_replicate([shape], sub)
        elif info.op_type == OperatorType.REDUCTION:
            (shape,), _ = _infer_reduction([shape], sub)
        else:
            raise ValueError(f"fused parallel: bad step {info.op_type}")
    return (shape,), ()


register_op(OperatorType.FUSED_PARALLEL, _infer_fused_parallel, _identity_lower)

_FOLDABLE = {
    OperatorType.REPARTITION,
    OperatorType.COMBINE,
    OperatorType.REPLICATE,
    OperatorType.REDUCTION,
    OperatorType.FUSED_PARALLEL,
}


def _chain_of(node) -> Tuple[ParallelOpInfo, ...]:
    if node.op_type == OperatorType.FUSED_PARALLEL:
        return tuple(node.params["chain"])
    return (
        ParallelOpInfo(
            node.op_type,
            node.params.get("axis", 0),
            node.params["degree"],
            node.params.get("parallel_idx", -1),
        ),
    )


def fold_parallel_ops(graph) -> int:
    """Fold runs of adjacent single-consumer parallel ops into one
    FUSED_PARALLEL node (reference: fused_parallel_op.cc applies a chain
    of ParallelOpInfos in one task — here one node means ONE sharding
    constraint for the whole re-layout, letting GSPMD emit a single fused
    collective instead of a chain). Returns the number of folds. Callers
    re-propagate shapes after."""
    from flexflow_tpu.core.pcg import TensorRef

    folded = 0
    changed = True
    while changed:
        changed = False
        for guid in list(graph.topo_order()):
            node = graph.nodes.get(guid)
            if node is None or node.op_type not in _FOLDABLE:
                continue
            cons = graph.consumers(guid)
            if len(cons) != 1:
                continue
            nxt = graph.nodes[next(iter(cons))]
            if nxt.op_type not in _FOLDABLE:
                continue
            chain = _chain_of(node) + _chain_of(nxt)
            fused = graph.add_node(
                OperatorType.FUSED_PARALLEL,
                f"{node.name}+{nxt.name}",
                [node.inputs[0]],
                {"chain": chain},
                list(nxt.output_shapes),
            )
            new_ref = TensorRef(fused.guid, 0)
            for c in list(graph.consumers(nxt.guid)):
                graph.replace_input(c, TensorRef(nxt.guid, 0), new_ref)
            graph.remove_node(nxt.guid)
            graph.remove_node(guid)
            folded += 1
            changed = True
            break
    return folded


# ---------------------------------------------------------------------------
# Pipeline (OP_PIPELINE) — declared but UNIMPLEMENTED in the reference
# (ffconst.h:151, PIPELINE_*_TASK_ID model.h:186-188 with no operator);
# here it is a stage-boundary marker: the pipeline scheduler
# (flexflow_tpu.parallel.pipeline) runs GPipe over the `pipe` mesh axis,
# and this node records where stages cut the graph.
# ---------------------------------------------------------------------------


def _infer_pipeline(input_shapes, params):
    return (input_shapes[0],), ()


register_op(OperatorType.PIPELINE, _infer_pipeline, _identity_lower)
