"""Python side of the C API (native/src/flexflow_c.cc).

The C layer embeds CPython and calls these flat helpers with primitive
arguments only (ints, floats, strings, raw addresses) — all object
plumbing stays here. Mirrors the role of the reference's flexflow_c.cc
body (reference: python/flexflow_c.cc:1884 LoC of handle unwrapping).
"""

from __future__ import annotations

import ctypes
import os
from typing import Sequence

import numpy as np


def _maybe_force_platform():
    """The embedded interpreter cannot rely on conftest: honor
    FF_CAPI_PLATFORM (e.g. "cpu") before any backend touch."""
    plat = os.environ.get("FF_CAPI_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


_maybe_force_platform()

from flexflow_tpu import (  # noqa: E402
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.core.types import PoolType  # noqa: E402

_ACTI = {
    0: ActiMode.NONE,
    1: ActiMode.RELU,
    2: ActiMode.SIGMOID,
    3: ActiMode.TANH,
    4: ActiMode.GELU,
}
_LOSS = {
    "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}
_METRIC = {
    "accuracy": MetricsType.ACCURACY,
    "sparse_categorical_crossentropy": MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
}


def config_create(argv: Sequence[str]) -> FFConfig:
    return FFConfig.parse_args(list(argv))


def model_create(config: FFConfig) -> FFModel:
    return FFModel(config)


def tensor_create(model: FFModel, dims: Sequence[int], name: str):
    return model.create_tensor(list(dims), name=name or None)


def add_dense(model, t, out_features, activation, use_bias):
    return model.dense(
        t, out_features, activation=_ACTI[activation], use_bias=bool(use_bias)
    )


def add_conv2d(model, t, oc, kh, kw, sh, sw, ph, pw, activation):
    return model.conv2d(
        t, oc, kh, kw, sh, sw, ph, pw, activation=_ACTI[activation]
    )


def add_pool2d(model, t, kh, kw, sh, sw, ph, pw, pool_type):
    return model.pool2d(
        t, kh, kw, sh, sw, ph, pw,
        pool_type=PoolType.MAX if pool_type == 0 else PoolType.AVG,
    )


def add_flat(model, t):
    return model.flat(t)


def add_embedding(model, t, num_entries, out_dim):
    return model.embedding(t, num_entries, out_dim)


def add_multihead_attention(model, q, k, v, embed_dim, num_heads):
    return model.multihead_attention(q, k, v, embed_dim, num_heads)


def add_unary(model, op: str, t):
    return getattr(model, op)(t)


def add_binary(model, op: str, a, b):
    return getattr(model, op)(a, b)


def add_softmax(model, t):
    return model.softmax(t)


def add_dropout(model, t, rate):
    return model.dropout(t, rate=float(rate))


def compile_model(model, loss: str, metrics: str, learning_rate: float):
    if loss not in _LOSS:
        raise ValueError(f"unknown loss {loss!r}; one of {sorted(_LOSS)}")
    mets = []
    for m in (metrics or "").split(","):
        m = m.strip()
        if m:
            if m not in _METRIC:
                raise ValueError(f"unknown metric {m!r}")
            mets.append(_METRIC[m])
    model.compile(
        optimizer=SGDOptimizer(lr=learning_rate),
        loss_type=_LOSS[loss],
        metrics=mets,
    )


def _array_from_ptr(addr: int, shape, dtype) -> np.ndarray:
    n = int(np.prod(shape))
    itemsize = np.dtype(dtype).itemsize
    buf = (ctypes.c_char * (n * itemsize)).from_address(addr)
    # copy: the caller's buffer lifetime ends when the C call returns
    return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()


def fit_ptr(
    model,
    x_addr: int,
    x_shape,
    y_addr: int,
    y_shape,
    y_is_int: int,
    epochs: int,
) -> float:
    x = _array_from_ptr(x_addr, tuple(x_shape), np.float32)
    y = _array_from_ptr(
        y_addr, tuple(y_shape), np.int32 if y_is_int else np.float32
    )
    hist = model.fit(x, y, epochs=int(epochs), verbose=False)
    last = hist[-1]
    return float(last["loss_sum"] / max(last["train_all"], 1))
