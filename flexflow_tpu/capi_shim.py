"""Python side of the C API (native/src/flexflow_c.cc).

The C layer embeds CPython and calls these flat helpers with primitive
arguments only (ints, floats, strings, raw addresses) — all object
plumbing stays here. Mirrors the role of the reference's flexflow_c.cc
body (reference: python/flexflow_c.cc:1884 LoC of handle unwrapping),
now at entry-point parity with the reference header's ~140 flexflow_*
functions (python/flexflow_c.h:80-681): per-layer constructors for every
op class, optimizer/initializer handles, parameter host I/O, dataloader
verbs, and the training-loop verbs.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
from typing import Dict, Optional, Sequence

import numpy as np


def _maybe_force_platform():
    """The embedded interpreter cannot rely on conftest: honor
    FF_CAPI_PLATFORM (e.g. "cpu") before any backend touch."""
    plat = os.environ.get("FF_CAPI_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


_maybe_force_platform()

from flexflow_tpu import (  # noqa: E402
    ActiMode,
    AdamOptimizer,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.core.types import AggrMode, PoolType  # noqa: E402
from flexflow_tpu.runtime.initializer import (  # noqa: E402
    ConstantInitializer,
    GlorotUniform,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)

_ACTI = {
    0: ActiMode.NONE,
    1: ActiMode.RELU,
    2: ActiMode.SIGMOID,
    3: ActiMode.TANH,
    4: ActiMode.GELU,
}
_LOSS = {
    "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}
_METRIC = {
    "accuracy": MetricsType.ACCURACY,
    "sparse_categorical_crossentropy": MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
}
_DTYPE = {0: DataType.FLOAT, 1: DataType.INT32, 2: DataType.INT64}
_AGGR = {0: AggrMode.NONE, 1: AggrMode.SUM, 2: AggrMode.AVG}


# -- config / model ----------------------------------------------------------


def config_create(argv: Sequence[str]) -> FFConfig:
    return FFConfig.parse_args(list(argv))


def config_get_batch_size(cfg):
    return int(cfg.batch_size)


def config_get_epochs(cfg):
    return int(cfg.epochs)


def config_get_num_nodes(cfg):
    return int(cfg.num_nodes)


def config_get_workers_per_node(cfg):
    return int(cfg.workers_per_node)


def model_create(config: FFConfig) -> FFModel:
    return FFModel(config)


# -- tensors -----------------------------------------------------------------


def tensor_create(model: FFModel, dims: Sequence[int], dtype: int, name: str):
    return model.create_tensor(
        list(dims), dtype=_DTYPE.get(dtype, DataType.FLOAT), name=name or None
    )


def tensor_num_dims(t):
    return len(t.dims)


def tensor_dims(t):
    return [int(d) for d in t.dims]


def tensor_dtype(t):
    for code, dt in _DTYPE.items():
        if dt == t.dtype:
            return code
    return -1


class OpHandle:
    """Opaque op handle (reference: flexflow_op_t is an Op*)."""

    def __init__(self, model: FFModel, guid: int):
        self.model = model
        self.guid = guid

    @property
    def node(self):
        return self.model.graph.nodes[self.guid]


class ParamHandle:
    """Opaque parameter handle (reference: flexflow_parameter_t)."""

    def __init__(self, model: FFModel, guid: int, idx: int):
        self.model = model
        self.guid = guid
        self.idx = idx


def _stage(model, name: str, arr):
    """THE batch-staging point: every write that changes what the next
    forward sees (inputs, labels, constants, dataloader batches) goes
    through here so the cached activations/gradients are invalidated
    together."""
    staged = getattr(model, "_capi_batch", None) or {}
    staged[name] = arr
    model._capi_batch = staged
    _invalidate(model)


def _invalidate(model):
    model._capi_values = None
    model._capi_grads = None


def tensor_owner_op(t):
    return OpHandle(t.model, t.ref.guid)


def tensor_attach_raw_ptr(model, t, addr, shape, is_int):
    arr = _array_from_ptr(
        addr, tuple(shape), np.int32 if is_int else np.float32
    )
    _stage(model, model.graph.nodes[t.ref.guid].name, arr)


def tensor_detach_raw_ptr(model, t):
    name = model.graph.nodes[t.ref.guid].name
    getattr(model, "_capi_batch", {}).pop(name, None)


# -- initializers ------------------------------------------------------------


def initializer_create(kind: str, seed: int, a: float, b: float, c: float):
    if kind == "glorot":
        return GlorotUniform(seed=seed)
    if kind == "zero":
        return ZeroInitializer()
    if kind == "uniform":
        return UniformInitializer(seed=seed, min_val=a, max_val=b)
    if kind == "norm":
        return NormInitializer(seed=seed, mean=a, stddev=b)
    if kind == "constant":
        return ConstantInitializer(a)
    raise ValueError(f"unknown initializer kind {kind!r}")


# -- optimizers --------------------------------------------------------------


class OptHandle:
    """Mutable wrapper (the framework's optimizers are frozen
    dataclasses; reference set_lr mutates in place, so the handle
    rebinds — and propagates to compiled models it is bound to, matching
    the reference's mid-training LR-decay pattern)."""

    def __init__(self, opt):
        self.opt = opt
        self.models = []  # FFModels bound via model_set_optimizer


def sgd_optimizer_create(lr, momentum, nesterov, weight_decay):
    return OptHandle(
        SGDOptimizer(
            lr=lr,
            momentum=momentum,
            nesterov=bool(nesterov),
            weight_decay=weight_decay,
        )
    )


def adam_optimizer_create(alpha, beta1, beta2, weight_decay, epsilon):
    return OptHandle(
        AdamOptimizer(
            alpha=alpha,
            beta1=beta1,
            beta2=beta2,
            weight_decay=weight_decay,
            epsilon=epsilon,
        )
    )


def optimizer_set_lr(handle: OptHandle, lr: float):
    field = "alpha" if isinstance(handle.opt, AdamOptimizer) else "lr"
    handle.opt = dataclasses.replace(handle.opt, **{field: lr})
    for model in handle.models:
        if model.executor is not None:
            # already compiled: route through the one LR-mutation path
            # (FFModel.set_learning_rate handles the field dispatch and
            # jitted-step invalidation); handle.opt was already updated
            # above and stays authoritative
            model.set_learning_rate(lr)


def model_set_optimizer(model, handle: OptHandle):
    model._capi_optimizer = handle
    if model not in handle.models:
        handle.models.append(model)


# -- layer builders ----------------------------------------------------------


def add_dense(model, t, out_features, activation, use_bias, kinit, binit):
    return model.dense(
        t,
        out_features,
        activation=_ACTI[activation],
        use_bias=bool(use_bias),
        kernel_initializer=kinit,
        bias_initializer=binit,
    )


def add_conv2d(
    model, t, oc, kh, kw, sh, sw, ph, pw, activation, groups, use_bias,
    kinit, binit,
):
    return model.conv2d(
        t, oc, kh, kw, sh, sw, ph, pw,
        activation=_ACTI[activation],
        groups=max(1, groups),
        use_bias=bool(use_bias),
        kernel_initializer=kinit,
        bias_initializer=binit,
    )


def add_pool2d(model, t, kh, kw, sh, sw, ph, pw, pool_type):
    return model.pool2d(
        t, kh, kw, sh, sw, ph, pw,
        pool_type=PoolType.MAX if pool_type == 0 else PoolType.AVG,
    )


def add_flat(model, t):
    return model.flat(t)


def add_embedding(model, t, num_entries, out_dim, aggr, kinit):
    return model.embedding(
        t,
        num_entries,
        out_dim,
        aggr=_AGGR.get(aggr, AggrMode.NONE),
        kernel_initializer=kinit,
    )


def add_multihead_attention(
    model, q, k, v, embed_dim, num_heads, kdim, vdim, dropout, bias, causal
):
    return model.multihead_attention(
        q, k, v, embed_dim, num_heads,
        kdim=kdim, vdim=vdim, dropout=float(dropout),
        bias=bool(bias), causal=bool(causal),
    )


def add_batch_matmul(model, a, b):
    return model.batch_matmul(a, b)


def add_batch_norm(model, t, relu):
    return model.batch_norm(t, relu=bool(relu))


def add_layer_norm(model, t, axes, elementwise_affine, eps):
    return model.layer_norm(
        t,
        axes=list(axes) or None,
        elementwise_affine=bool(elementwise_affine),
        eps=float(eps),
    )


def add_concat(model, tensors, axis):
    return model.concat(list(tensors), axis)


def add_split(model, t, sizes, axis):
    return list(model.split(t, list(sizes), axis))


def add_reshape(model, t, dims):
    return model.reshape(t, list(dims))


def add_transpose(model, t, perm):
    return model.transpose(t, list(perm))


def add_reverse(model, t, axis):
    return model.reverse(t, axis)


def add_mean(model, t, dims, keepdims):
    return model.mean(t, list(dims), keepdims=bool(keepdims))


def add_reduce_sum(model, t, dims, keepdims):
    return model.reduce_sum(t, list(dims), keepdims=bool(keepdims))


def add_cast(model, t, dtype):
    return model.cast(t, _DTYPE.get(dtype, DataType.FLOAT))


def add_unary(model, op: str, t):
    return getattr(model, op)(t)


def add_scalar_op(model, op: str, t, scalar):
    # C surface keeps the reference spelling "scalar_truediv"
    # (flexflow_c.h); the builder method is scalar_true_divide
    method = "scalar_true_divide" if op == "scalar_truediv" else op
    return getattr(model, method)(t, float(scalar))


def add_binary(model, op: str, a, b):
    return getattr(model, op)(a, b)


def add_softmax(model, t):
    return model.softmax(t)


def add_dropout(model, t, rate):
    return model.dropout(t, rate=float(rate))


# -- compile / train ---------------------------------------------------------


def compile_model(model, loss: str, metrics: str, learning_rate: float):
    if loss not in _LOSS:
        raise ValueError(f"unknown loss {loss!r}; one of {sorted(_LOSS)}")
    mets = []
    for m in (metrics or "").split(","):
        m = m.strip()
        if m:
            if m not in _METRIC:
                raise ValueError(f"unknown metric {m!r}")
            mets.append(_METRIC[m])
    handle = getattr(model, "_capi_optimizer", None)
    opt = handle.opt if handle is not None else SGDOptimizer(lr=learning_rate)
    model.compile(optimizer=opt, loss_type=_LOSS[loss], metrics=mets)


def _array_from_ptr(addr: int, shape, dtype) -> np.ndarray:
    n = int(np.prod(shape))
    itemsize = np.dtype(dtype).itemsize
    buf = (ctypes.c_char * (n * itemsize)).from_address(addr)
    # copy: the caller's buffer lifetime ends when the C call returns
    return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()


def _array_to_ptr(arr: np.ndarray, addr: int):
    arr = np.ascontiguousarray(arr)
    ctypes.memmove(addr, arr.ctypes.data, arr.nbytes)


def fit_ptr(
    model,
    x_addr: int,
    x_shape,
    y_addr: int,
    y_shape,
    y_is_int: int,
    epochs: int,
) -> float:
    # the C ABI's x is a single float buffer; FFModel._pack_dataset
    # coerces each input to its declared dtype (int ids for embeddings)
    x = _array_from_ptr(x_addr, tuple(x_shape), np.float32)
    y = _array_from_ptr(
        y_addr, tuple(y_shape), np.int32 if y_is_int else np.float32
    )
    hist = model.fit(x, y, epochs=int(epochs), verbose=False)
    last = hist[-1]
    return float(last["loss_sum"] / max(last["train_all"], 1))


# -- training-loop verbs (reference: flexflow_cffi fit loop) -----------------
#
# forward: inference on the staged batch; backward: run the fused
# grad+update step and HOLD the result; update: commit it. This preserves
# the reference call sequence's observable semantics (weights change at
# update) on a functional engine where grads and the optimizer live in
# one jitted program.


def _staged_batch(model) -> Dict[str, np.ndarray]:
    batch = getattr(model, "_capi_batch", None)
    if not batch:
        raise RuntimeError(
            "no batch staged: attach data via flexflow_tensor_attach_raw_ptr"
            " or a flexflow_single_dataloader"
        )
    return batch


def model_init_layers(model):
    model.init_operators()


def model_forward(model):
    logits = model.forward(_staged_batch(model))
    model._capi_logits = logits


def model_zero_gradients(model):
    model.zero_gradients()


def model_backward(model):
    import jax

    batch = _staged_batch(model)
    step = model.executor.train_step()
    sharded = model.executor.shard_batch(batch)
    model._rng, key = jax.random.split(model._rng)
    model._capi_pending = step(model.params, model.opt_state, sharded, key)


def model_update(model):
    pending = getattr(model, "_capi_pending", None)
    if pending is None:
        raise RuntimeError("flexflow_model_backward must run before update")
    model.params, model.opt_state, loss, _ = pending
    model._capi_last_loss = float(np.asarray(loss))
    model._capi_pending = None
    _invalidate(model)  # weights changed: cached activations/grads stale


def model_last_loss(model):
    return float(getattr(model, "_capi_last_loss", float("nan")))


# -- metrics -----------------------------------------------------------------


def model_reset_metrics(model):
    from flexflow_tpu.runtime.metrics import PerfMetrics

    model._capi_perf = PerfMetrics()


def model_compute_metrics(model):
    import jax

    from flexflow_tpu.runtime.metrics import PerfMetrics

    if getattr(model, "_capi_perf", None) is None:
        model._capi_perf = PerfMetrics()
    batch = _staged_batch(model)
    loss, mets = model.executor.eval_step()(
        model.params, model.executor.shard_batch(batch)
    )
    model._capi_perf.update(
        jax.tree_util.tree_map(float, mets), float(loss)
    )


def model_perf_metrics(model):
    from flexflow_tpu.runtime.metrics import PerfMetrics

    return getattr(model, "_capi_perf", None) or PerfMetrics()


def perf_metrics_accuracy(perf):
    total = max(getattr(perf, "train_all", 0), 1)
    return 100.0 * getattr(perf, "train_correct", 0) / total


# -- layer / parameter introspection -----------------------------------------


def _layer_guids(model):
    from flexflow_tpu.core.types import OperatorType

    return [
        g
        for g in model.graph.topo_order()
        if model.graph.nodes[g].op_type != OperatorType.INPUT
    ]


def model_num_layers(model):
    return len(_layer_guids(model))


def model_layer_by_id(model, idx):
    return OpHandle(model, _layer_guids(model)[idx])


def model_last_layer(model):
    return OpHandle(model, _layer_guids(model)[-1])


def model_print_layers(model):
    for g in _layer_guids(model):
        n = model.graph.nodes[g]
        print(f"{g}: {n.op_type.name} {n.name} -> "
              f"{[str(s) for s in n.output_shapes]}")


def op_num_inputs(op: OpHandle):
    return len(op.node.inputs)


def op_num_outputs(op: OpHandle):
    return len(op.node.output_shapes)


def op_num_parameters(op: OpHandle):
    return len(op.node.weight_shapes)


def op_input_by_id(op: OpHandle, idx):
    from flexflow_tpu.runtime.model import Tensor

    return Tensor(op.model, op.node.inputs[idx])


def op_output_by_id(op: OpHandle, idx):
    from flexflow_tpu.core.pcg import TensorRef
    from flexflow_tpu.runtime.model import Tensor

    return Tensor(op.model, TensorRef(op.guid, idx))


def op_parameter_by_id(op: OpHandle, idx):
    if idx >= len(op.node.weight_shapes):
        raise IndexError(f"op has {len(op.node.weight_shapes)} parameters")
    return ParamHandle(op.model, op.guid, idx)


def parameter_num_elements(p: ParamHandle):
    shape = p.model.graph.nodes[p.guid].weight_shapes[p.idx]
    return int(
        np.prod([d.size for d in shape.dims if not d.is_replica_dim])
    )


def parameter_get_weights(p: ParamHandle, addr: int, count: int):
    w = p.model.get_tensor(p.guid, p.idx)
    if w.size != count:
        raise ValueError(f"parameter has {w.size} elements, buffer {count}")
    _array_to_ptr(w.astype(np.float32), addr)


def parameter_set_weights(p: ParamHandle, addr: int, count: int):
    shape = p.model.graph.nodes[p.guid].weight_shapes[p.idx]
    dims = tuple(d.size for d in shape.dims if not d.is_replica_dim)
    if int(np.prod(dims)) != count:
        raise ValueError(
            f"parameter has {int(np.prod(dims))} elements, buffer {count}"
        )
    arr = _array_from_ptr(addr, dims, np.float32)
    p.model.set_tensor(p.guid, p.idx, arr)


# -- dataloader --------------------------------------------------------------


class CApiDataLoader:
    """Host dataloader staging fixed-size batches into the model's
    staged batch (reference: SingleDataLoader next_batch index-launches,
    python/flexflow_dataloader.cc; here the jitted step consumes the
    staged arrays)."""

    def __init__(self, model, name: str, data: np.ndarray):
        self.model = model
        self.name = name
        self.data = data
        self.num_samples = int(data.shape[0])
        self.batch_size = int(model.config.batch_size)
        self.index = 0

    def reset(self):
        self.index = 0

    def next_batch(self):
        b = self.batch_size
        if self.num_samples < b:
            raise RuntimeError(
                f"dataloader num_samples {self.num_samples} < batch size "
                f"{b}; a short batch would change the jitted step's shapes"
            )
        if self.index + b > self.num_samples:
            self.index = 0
        sl = self.data[self.index : self.index + b]
        self.index += b
        _stage(self.model, self.name, sl)


def dataloader_create(model, t, addr, shape, is_int):
    data = _array_from_ptr(
        addr, tuple(shape), np.int32 if is_int else np.float32
    )
    name = model.graph.nodes[t.ref.guid].name
    return CApiDataLoader(model, name, data)


def dataloader_create_label(model, addr, shape, is_int):
    data = _array_from_ptr(
        addr, tuple(shape), np.int32 if is_int else np.float32
    )
    return CApiDataLoader(model, "label", data)


def dataloader_num_samples(loader):
    return loader.num_samples


def dataloader_set_num_samples(loader, num):
    loader.num_samples = min(int(num), int(loader.data.shape[0]))


def dataloader_reset(loader):
    loader.reset()


def dataloader_next_batch(loader):
    loader.next_batch()


# -- C API tail (reference parity, python/flexflow_c.h:59-669) ---------------

_NP_TAG = {"f4": np.float32, "i4": np.int32, "i8": np.int64}


def config_parse_args(cfg, argv: Sequence[str]):
    """Re-parse reference-spelling flags into an EXISTING config handle
    (reference: flexflow_config_parse_args)."""
    parsed = FFConfig.parse_args(list(argv))
    cfg.__dict__.update(vars(parsed))


class LabelTensor:
    """The compile()-created label tensor (reference:
    flexflow_model_get_label_tensor returns the label ParallelTensor).
    Tensor-protocol surface: dims/dtype plus staging under "label"."""

    def __init__(self, model: FFModel):
        self.model = model

    @property
    def _shape(self):
        ex = self.model.executor
        if ex is None or ex.label_shape is None:
            raise RuntimeError("call compile() before get_label_tensor()")
        return ex.label_shape

    @property
    def dims(self):
        return [
            d.size for d in self._shape.dims if not d.is_replica_dim
        ]

    @property
    def dtype(self):
        return self._shape.dtype


class ParamTensor:
    """A parameter exposed through the TENSOR protocol (reference:
    flexflow_model_get_parameter_by_id returns a Tensor)."""

    def __init__(self, model: FFModel, guid: int, idx: int = 0):
        self.model = model
        self.guid = guid
        self.idx = idx

    @property
    def _shape(self):
        return self.model.graph.nodes[self.guid].weight_shapes[self.idx]

    @property
    def dims(self):
        return [
            d.size for d in self._shape.dims if not d.is_replica_dim
        ]

    @property
    def dtype(self):
        return self._shape.dtype


def model_get_label_tensor(model):
    return LabelTensor(model)


def model_get_parameter_by_id(model, layer_id: int):
    guid = _layer_guids(model)[layer_id]
    node = model.graph.nodes[guid]
    if not node.weight_shapes:
        raise ValueError(f"layer {layer_id} ({node.name}) has no parameters")
    return ParamTensor(model, guid, 0)


def constant_create(model, dims: Sequence[int], value: float, dtype: int):
    """Constant-filled tensor: an input-protocol tensor whose staged value
    is permanently the constant array (reference: flexflow_constant_create
    maps and fills a Legion region; here the jitted step consumes the
    staged array each step)."""
    dt = _DTYPE.get(dtype, DataType.FLOAT)
    t = model.create_tensor(list(dims), dtype=dt, name=None)
    np_dt = {
        DataType.FLOAT: np.float32,
        DataType.INT32: np.int32,
        DataType.INT64: np.int64,
    }.get(dt, np.float32)
    arr = np.full(tuple(dims), value, dtype=np_dt)
    _stage(model, model.graph.nodes[t.ref.guid].name, arr)
    return t


def tensor_get_dim_legion(t, legion_axis: int):
    """Single dim in the reference's Legion order (innermost first)."""
    dims = list(t.dims)
    return int(dims[len(dims) - 1 - legion_axis])


def _staged_batch(model):
    staged = getattr(model, "_capi_batch", None)
    if not staged:
        raise RuntimeError(
            "no data staged: attach raw ptrs / run a dataloader batch first"
        )
    return staged


def op_init(op: OpHandle, model):
    """reference: flexflow_op_init launches the op's init task. Parameters
    here materialize at compile() (functional runtime), so init is
    intentionally a no-op that just validates the handle."""
    _ = op.node
    return 0


def op_forward(op: OpHandle, model):
    """reference: flexflow_op_forward runs one op's forward task. XLA
    executes the whole fused program, so this evaluates the graph forward
    on the staged batch and caches every activation; per-op reads go
    through tensor_get_tensor."""
    ex = model.executor
    if ex is None:
        raise RuntimeError("call compile() before op_forward()")
    batch = ex.shard_batch(dict(_staged_batch(model)))
    model._capi_values = ex.forward_values(
        model.params, batch, train=False
    )
    return 0


def tensor_set_tensor(model, t, dims: Sequence[int], addr: int, tag: str):
    """Host->tensor write by handle (reference:
    flexflow_tensor_set_tensor_*): parameters write weights; graph input
    tensors stage batch data."""
    arr = _array_from_ptr(addr, tuple(dims), _NP_TAG[tag]).copy()
    if isinstance(t, ParamTensor):
        model.set_tensor(t.guid, t.idx, arr)
        _invalidate(model)  # activations depend on the weights too
        return 0
    if isinstance(t, LabelTensor):
        _stage(model, "label", arr)
        return 0
    node = model.graph.nodes[t.ref.guid]
    if node.inputs:
        raise ValueError(
            "set_tensor targets parameters, inputs, or the label tensor; "
            f"{node.name} is an interior op output"
        )
    _stage(model, node.name, arr)
    return 0


def tensor_get_tensor(model, t, addr: int, tag: str, get_gradients: int):
    """Tensor->host read by handle (reference:
    flexflow_tensor_get_tensor_*). Parameters read weights (or their loss
    gradient on the staged batch with get_gradients); interior tensors
    read the activation cached by op_forward/model_forward."""
    dt = _NP_TAG[tag]
    if isinstance(t, ParamTensor):
        if get_gradients:
            grads = getattr(model, "_capi_grads", None)
            if grads is None:
                staged = _staged_batch(model)
                if "label" not in staged:
                    raise RuntimeError(
                        "stage labels (set_tensor on the label tensor or "
                        "a label dataloader batch) before reading "
                        "gradients"
                    )
                xs = {k: v for k, v in staged.items() if k != "label"}
                # ONE fwd+bwd serves every parameter read until the
                # staged batch or a weight changes (_invalidate)
                grads = model.compute_gradients(xs, staged["label"])
                model._capi_grads = grads
            arr = np.asarray(grads[t.guid][t.idx])
        else:
            arr = np.asarray(model.get_tensor(t.guid, t.idx))
    elif isinstance(t, LabelTensor):
        arr = np.asarray(_staged_batch(model)["label"])
    else:
        if get_gradients:
            raise ValueError(
                "activation gradients are not retained (functional "
                "autodiff); read parameter gradients instead"
            )
        guid = t.ref.guid
        node = model.graph.nodes.get(guid)
        if node is not None and not node.inputs:
            arr = np.asarray(_staged_batch(model)[node.name])
        else:
            values = getattr(model, "_capi_values", None)
            if values is None or (guid, t.ref.out_idx) not in values:
                op_forward(OpHandle(model, guid), model)
                values = model._capi_values
            arr = np.asarray(values[(guid, t.ref.out_idx)])
    _array_to_ptr(np.ascontiguousarray(arr, dtype=dt), addr)
    return 0


def dataloader_create2(model, t, addr: int, num_samples: int, is_int: int):
    """Raw-pointer dataloader (reference: create2): the per-sample shape
    comes from the attached tensor; the leading dim is num_samples."""
    sample_dims = list(t.dims)[1:]
    data = _array_from_ptr(
        addr,
        tuple([int(num_samples)] + sample_dims),
        np.int32 if is_int else np.float32,
    )
    if isinstance(t, LabelTensor):
        return CApiDataLoader(model, "label", data)
    name = model.graph.nodes[t.ref.guid].name
    return CApiDataLoader(model, name, data)
