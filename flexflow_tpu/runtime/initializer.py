"""Weight initializers (reference: src/runtime/initializer.cc,
initializer_kernel.cu — Glorot-uniform, Zero, Constant, Uniform, Normal as
device tasks; here each is a pure function of a PRNG key)."""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from flexflow_tpu.core.parallel_tensor import ParallelTensorShape


@dataclasses.dataclass(frozen=True)
class Initializer:
    def create(self, key, shape: ParallelTensorShape):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class GlorotUniform(Initializer):
    """reference: GlorotUniform in initializer.cc — limit sqrt(6/(fi+fo))."""

    seed: int = 0

    def create(self, key, shape: ParallelTensorShape):
        sizes = shape.logical_sizes
        if len(sizes) >= 2:
            fan_in = math.prod(sizes[:-1])
            fan_out = sizes[-1]
        else:
            fan_in = fan_out = sizes[0]
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(
            key, sizes, shape.dtype.to_jnp(), minval=-limit, maxval=limit
        )


@dataclasses.dataclass(frozen=True)
class ZeroInitializer(Initializer):
    def create(self, key, shape: ParallelTensorShape):
        return jnp.zeros(shape.logical_sizes, shape.dtype.to_jnp())


@dataclasses.dataclass(frozen=True)
class ConstantInitializer(Initializer):
    value: float = 0.0

    def create(self, key, shape: ParallelTensorShape):
        return jnp.full(shape.logical_sizes, self.value, shape.dtype.to_jnp())


@dataclasses.dataclass(frozen=True)
class UniformInitializer(Initializer):
    min_val: float = 0.0
    max_val: float = 1.0
    seed: int = 0

    def create(self, key, shape: ParallelTensorShape):
        return jax.random.uniform(
            key,
            shape.logical_sizes,
            shape.dtype.to_jnp(),
            minval=self.min_val,
            maxval=self.max_val,
        )


@dataclasses.dataclass(frozen=True)
class NormInitializer(Initializer):
    mean: float = 0.0
    stddev: float = 1.0
    seed: int = 0

    def create(self, key, shape: ParallelTensorShape):
        return (
            self.mean
            + self.stddev
            * jax.random.normal(key, shape.logical_sizes).astype(
                shape.dtype.to_jnp()
            )
        )


def default_weight_initializer(
    op_name: str, idx: int, shape: ParallelTensorShape = None
) -> Initializer:
    """Matrix-shaped weights (rank >= 2: kernels, all four MHA projections)
    get Glorot; vector weights (biases, LN beta) get zeros — matching the
    reference's per-op defaults (e.g. linear.cc kernel_initializer /
    bias_initializer). Scale-style vectors (gamma) must be requested
    explicitly as ConstantInitializer(1.0) by the builder."""
    if shape is not None:
        return (
            GlorotUniform()
            if len(shape.logical_sizes) >= 2
            else ZeroInitializer()
        )
    return GlorotUniform() if idx == 0 else ZeroInitializer()
