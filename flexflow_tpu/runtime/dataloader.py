"""Data loading (reference: python/flexflow_dataloader.{h,cc,cu} —
SingleDataLoader keeps the full dataset in zero-copy memory and
index-launches per-shard batch copies; SURVEY §2.7).

TPU-native version: the dataset lives in host RAM as numpy arrays; each
`next_batch` slices a global batch and `jax.device_put`s it with the input's
NamedSharding, so each chip receives exactly its shard (the same
host→device movement pattern, without the Legion tasks). Batch assembly
(shuffle + row gather) runs on the native threaded loader
(native/src/dataloader.cc via flexflow_tpu.native.NativeLoader) when the
C++ core is available, so the next batch is prefetched while the chip is
still executing the current step — the role the reference's background
CPU load tasks played."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class SingleDataLoader:
    """Full-dataset-resident loader with sequential batches
    (reference: flexflow_dataloader.h:34-107)."""

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        use_native: bool = True,
    ):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"dataset arrays disagree on length: {sizes}")
        self.arrays = arrays
        self.num_samples = next(iter(sizes.values()))
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.RandomState(seed)
        self._order = np.arange(self.num_samples)
        self._pos = 0
        self._native = None
        # Native prefetch path: only for full-batch epochs (drop_last) so
        # both paths produce identical batch shapes, and only when at least
        # one full batch exists. The permutation always comes from this
        # object's numpy RNG, so batches are bit-identical with or without
        # the native library.
        if (
            use_native
            and drop_last
            and self.num_samples >= batch_size
        ):
            from flexflow_tpu import native as _native_mod

            if _native_mod.available():
                self._keys = list(arrays.keys())
                self._native = _native_mod.NativeLoader(
                    [arrays[k] for k in self._keys],
                    batch_size,
                    shuffle=False,  # order supplied via reset_perm below
                    seed=seed,
                    drop_last=drop_last,
                )
                self._native.reset_perm(self._order)

    @property
    def num_batches(self) -> int:
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def reset(self):
        self._pos = 0
        if self.shuffle:
            self._rng.shuffle(self._order)
        if self._native is not None:
            self._native.reset_perm(self._order)

    def next_batch(self) -> Dict[str, np.ndarray]:
        if self._native is not None:
            bufs = self._native.next_batch()
            if bufs is None:  # epoch rollover
                self.reset()
                bufs = self._native.next_batch()
            return dict(zip(self._keys, bufs))
        remaining = self.num_samples - self._pos
        if remaining < self.batch_size and (self.drop_last or remaining == 0):
            self.reset()
        take = self.batch_size
        if not self.drop_last:
            take = min(take, self.num_samples - self._pos)
        idx = self._order[self._pos : self._pos + take]
        self._pos += take
        return {k: v[idx] for k, v in self.arrays.items()}

    def __iter__(self):
        self.reset()
        for _ in range(self.num_batches):
            yield self.next_batch()


def synthetic_dataset(
    input_specs: Dict[str, tuple],
    num_samples: int,
    seed: int = 0,
) -> Dict[str, np.ndarray]:
    """Random data matching {name: (shape_without_batch, np.dtype, high)}.

    Integer dtypes draw uniform ints in [0, high); floats draw N(0, 1).
    """
    rng = np.random.RandomState(seed)
    out = {}
    for name, (shape, dtype, high) in input_specs.items():
        full = (num_samples,) + tuple(shape)
        if np.issubdtype(np.dtype(dtype), np.integer):
            out[name] = rng.randint(0, max(1, int(high)), size=full).astype(dtype)
        else:
            out[name] = rng.randn(*full).astype(dtype)
    return out
