"""Training metrics (reference: src/metrics_functions/ — Metrics::compute
launches per-shard METRICS_COMP tasks whose PerfMetrics are reduced through a
Legion future chain, model.cc:741; here metrics are computed inside the jitted
step and the host accumulates a PerfMetrics counter)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import jax
import jax.numpy as jnp

from flexflow_tpu.core.types import MetricsType


def compute_metrics(
    metric_types: Sequence[MetricsType], logits, labels, from_logits: bool = False
) -> Dict[str, jnp.ndarray]:
    """Returns summed (not averaged) per-batch metric values + counts, so the
    host can accumulate exactly like PerfMetrics (metrics_functions.h:12-28).

    from_logits: the final op emits raw logits (no softmax); CE metrics go
    through log_softmax instead of log(probs), mirroring compute_loss.
    """
    out = {}
    n = logits.shape[0] if logits.ndim > 0 else 1
    out["num_samples"] = jnp.asarray(n, jnp.float32)

    if (
        labels.ndim == logits.ndim
        and labels.shape[-1] == 1
        and logits.shape[-1] != 1
    ):
        # reference label tensors are [batch, 1] sparse class indices
        # (loss_functions.cc) — squeeze so they aren't read as one-hot
        labels = labels[..., 0]

    def _logp():
        x = jnp.asarray(logits, jnp.float32)
        if from_logits:
            return jax.nn.log_softmax(x, axis=-1)
        return jnp.log(jnp.clip(x, 1e-12, 1.0))

    for mt in metric_types:
        if mt == MetricsType.ACCURACY:
            if labels.ndim == logits.ndim:  # one-hot
                correct = jnp.argmax(logits, -1) == jnp.argmax(labels, -1)
            else:
                correct = jnp.argmax(logits, -1) == labels.astype(jnp.int32)
            out["accuracy_sum"] = jnp.sum(correct.astype(jnp.float32))
        elif mt == MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY:
            logp = jnp.take_along_axis(
                _logp(), labels.astype(jnp.int32)[..., None], axis=-1
            )
            out["ce_sum"] = out.get("ce_sum", 0.0) + (-jnp.sum(logp))
        elif mt == MetricsType.CATEGORICAL_CROSSENTROPY:
            out["ce_sum"] = out.get("ce_sum", 0.0) + (-jnp.sum(labels * _logp()))
        elif mt == MetricsType.MEAN_SQUARED_ERROR:
            out["mse_sum"] = jnp.sum(
                jnp.square(jnp.asarray(logits, jnp.float32) - labels)
            )
        elif mt == MetricsType.ROOT_MEAN_SQUARED_ERROR:
            out["rmse_sum"] = jnp.sqrt(
                jnp.mean(jnp.square(jnp.asarray(logits, jnp.float32) - labels))
            ) * n
        elif mt == MetricsType.MEAN_ABSOLUTE_ERROR:
            out["mae_sum"] = jnp.sum(
                jnp.abs(jnp.asarray(logits, jnp.float32) - labels)
            )
    return out


@dataclasses.dataclass
class PerfMetrics:
    """Host-side accumulator (reference: metrics_functions.h:12-28)."""

    train_all: int = 0
    train_correct: float = 0.0
    ce_loss: float = 0.0
    mse_loss: float = 0.0
    mae_loss: float = 0.0
    loss_sum: float = 0.0
    iterations: int = 0

    def update(self, step_metrics: Dict[str, float], loss: float):
        n = int(step_metrics.get("num_samples", 0))
        self.train_all += n
        self.train_correct += float(step_metrics.get("accuracy_sum", 0.0))
        self.ce_loss += float(step_metrics.get("ce_sum", 0.0))
        self.mse_loss += float(step_metrics.get("mse_sum", 0.0))
        self.mae_loss += float(step_metrics.get("mae_sum", 0.0))
        self.loss_sum += float(loss) * max(n, 1)
        self.iterations += 1

    def get_accuracy(self) -> float:
        """Training accuracy in percent (reference:
        flexflow_per_metrics_get_accuracy, flexflow_cffi.py:2227 — the
        value VerifyMetrics callbacks compare against their target)."""
        return 100.0 * self.train_correct / max(self.train_all, 1)

    def report(self) -> str:
        n = max(self.train_all, 1)
        parts = [f"loss: {self.loss_sum / n:.4f}"]
        if self.train_correct:
            parts.append(
                f"accuracy: {self.get_accuracy():.2f}%"
                f" ({int(self.train_correct)} / {n})"
            )
        if self.ce_loss:
            parts.append(f"ce: {self.ce_loss / n:.4f}")
        if self.mse_loss:
            parts.append(f"mse: {self.mse_loss / n:.4f}")
        return " ".join(parts)
