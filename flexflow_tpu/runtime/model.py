"""FFModel: the layer-builder + compile/fit API.

Re-design of the reference's FFModel (reference: include/flexflow/model.h:321,
builder methods model.h:331-532; Python mirror python/flexflow/core/
flexflow_cffi.py:815). The builder records PCG nodes; `compile()` picks a
parallelization strategy (data-parallel default, reference:
graph.cc:1588-1613; or the Unity-style search when a budget is given),
propagates parallel shapes, and lowers to a jitted XLA train step through
`runtime.executor.Executor`.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.parallel_tensor import ParallelDim, ParallelTensorShape
from flexflow_tpu.core.pcg import PCGGraph, PCGNode, TensorRef
from flexflow_tpu.core.types import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
)
from flexflow_tpu.ops.registry import _ensure_registered, infer_shapes
from flexflow_tpu.runtime.dataloader import SingleDataLoader
from flexflow_tpu.runtime.initializer import ConstantInitializer, ZeroInitializer
from flexflow_tpu.runtime.executor import Executor, MeshConfig, propagate_shapes
from flexflow_tpu.runtime.metrics import PerfMetrics
from flexflow_tpu.runtime.optimizer import Optimizer, SGDOptimizer


class Tensor:
    """Handle to one PCG tensor (reference: TensorBase, tensor.h:30-80)."""

    def __init__(self, model: "FFModel", ref: TensorRef):
        self.model = model
        self.ref = ref

    @property
    def shape(self) -> ParallelTensorShape:
        return self.model.graph.shape_of(self.ref)

    @property
    def dims(self):
        return self.shape.logical_sizes

    @property
    def dtype(self) -> DataType:
        return self.shape.dtype

    def __repr__(self):
        return f"Tensor(guid={self.ref.guid}, {self.shape})"


class TensorDataLoader:
    """Handle returned by FFModel.create_data_loader (reference:
    SingleDataLoader, flexflow_cffi.py:2281 — the full dataset bound to
    one tensor; fit() consumes these per-tensor handles)."""

    def __init__(self, name: str, array):
        self.name = name
        self.array = np.asarray(array)
        self.num_samples = int(self.array.shape[0])

    def __repr__(self):
        return (
            f"TensorDataLoader({self.name!r}, {self.array.shape}, "
            f"{self.array.dtype})"
        )


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        _ensure_registered()
        self.config = config or FFConfig()
        self.graph = PCGGraph()
        self._name_counts: Dict[str, int] = {}
        self._input_order: List[str] = []
        self.executor: Optional[Executor] = None
        self.params = None
        self.opt_state = None
        # host-side cache-op memoization (reference: src/ops/cache.cc)
        self._cache_specs: Dict[str, tuple] = {}
        self._cache_state: Dict[str, list] = {}
        self._cache_scores: Dict[str, float] = {}
        self.optimizer: Optional[Optimizer] = None
        self.loss_type: Optional[LossType] = None
        self.metric_types: Sequence[MetricsType] = ()
        self.label_dtype = DataType.INT32
        self._rng = jax.random.PRNGKey(self.config.seed)
        self._logits: Optional[Tensor] = None
        self.strategy = None  # filled by compile()
        self.search_trace = None  # filled by search_strategy (--search-trace)
        # recompile_on_condition fires (runtime/recompile.py) — mirrored
        # into the train_recompiles_total telemetry counter by fit()
        self.recompile_events = 0

    # ------------------------------------------------------------------ util

    def _unique_name(self, base: str, name: Optional[str]) -> str:
        if name:
            return name
        n = self._name_counts.get(base, 0)
        self._name_counts[base] = n + 1
        return f"{base}_{n}" if n else base

    def _add(self, op_type, name_base, inputs, params, name=None) -> List[Tensor]:
        name = self._unique_name(name_base, name)
        in_shapes = [self.graph.shape_of(t.ref) for t in inputs]
        outs, weights = infer_shapes(op_type, in_shapes, params)
        node = self.graph.add_node(
            op_type,
            name,
            [t.ref for t in inputs],
            params,
            outs,
            weights,
        )
        return [Tensor(self, TensorRef(node.guid, i)) for i in range(len(outs))]

    # ----------------------------------------------------------- tensors

    def create_tensor(
        self,
        dims: Sequence[int],
        dtype: DataType = DataType.FLOAT,
        name: Optional[str] = None,
    ) -> Tensor:
        """reference: FFModel::create_tensor (model.h); dims in numpy order
        with dims[0] = batch."""
        name = self._unique_name("input", name)
        shape = ParallelTensorShape.make(tuple(dims), dtype)
        node = self.graph.add_node(
            OperatorType.INPUT, name, [], {"shape": shape}, [shape]
        )
        self._input_order.append(name)
        return Tensor(self, TensorRef(node.guid, 0))

    # ----------------------------------------------------------- layers
    # Each method mirrors one reference builder (model.h:331-532).

    def dense(
        self,
        input: Tensor,
        out_dim: int,
        activation: ActiMode = ActiMode.NONE,
        use_bias: bool = True,
        kernel_initializer=None,
        bias_initializer=None,
        name: Optional[str] = None,
    ) -> Tensor:
        params = {
            "out_features": out_dim,
            "activation": activation,
            "use_bias": use_bias,
            "initializers": [kernel_initializer, bias_initializer]
            if use_bias
            else [kernel_initializer],
        }
        return self._add(OperatorType.LINEAR, "dense", [input], params, name)[0]

    def conv2d(
        self,
        input: Tensor,
        out_channels: int,
        kernel_h: int,
        kernel_w: int,
        stride_h: int = 1,
        stride_w: int = 1,
        padding_h: int = 0,
        padding_w: int = 0,
        activation: ActiMode = ActiMode.NONE,
        groups: int = 1,
        use_bias: bool = True,
        kernel_initializer=None,
        bias_initializer=None,
        name: Optional[str] = None,
    ) -> Tensor:
        params = {
            "out_channels": out_channels,
            "kernel_h": kernel_h,
            "kernel_w": kernel_w,
            "stride_h": stride_h,
            "stride_w": stride_w,
            "padding_h": padding_h,
            "padding_w": padding_w,
            "activation": activation,
            "groups": groups,
            "use_bias": use_bias,
            "initializers": [kernel_initializer, bias_initializer]
            if use_bias
            else [kernel_initializer],
        }
        return self._add(OperatorType.CONV2D, "conv2d", [input], params, name)[0]

    def pool2d(
        self,
        input: Tensor,
        kernel_h: int,
        kernel_w: int,
        stride_h: int = 1,
        stride_w: int = 1,
        padding_h: int = 0,
        padding_w: int = 0,
        pool_type: str = "max",
        activation: ActiMode = ActiMode.NONE,
        count_include_pad: bool = True,
        name: Optional[str] = None,
    ) -> Tensor:
        """count_include_pad: avg-pool divisor semantics — True divides by
        the full kernel area (torch AvgPool2d default), False by the
        in-bounds window count (keras/TF 'same', ONNX default)."""
        params = {
            "kernel_h": kernel_h,
            "kernel_w": kernel_w,
            "stride_h": stride_h,
            "stride_w": stride_w,
            "padding_h": padding_h,
            "padding_w": padding_w,
            "activation": activation,
            "count_include_pad": count_include_pad,
        }
        op = (
            OperatorType.POOL2D_MAX
            if str(pool_type).lower() in ("max", "pool_max")
            else OperatorType.POOL2D_AVG
        )
        return self._add(op, "pool2d", [input], params, name)[0]

    def batch_norm(
        self, input: Tensor, relu: bool = True, name: Optional[str] = None
    ) -> Tensor:
        params = {
            "activation": ActiMode.RELU if relu else ActiMode.NONE,
            # gamma = ones, beta = zeros (reference batch_norm defaults)
            "initializers": [ConstantInitializer(1.0), None],
        }
        return self._add(OperatorType.BATCHNORM, "batch_norm", [input], params, name)[0]

    def layer_norm(
        self,
        input: Tensor,
        axes: Optional[Sequence[int]] = None,
        elementwise_affine: bool = True,
        eps: float = 1e-5,
        name: Optional[str] = None,
    ) -> Tensor:
        ndim = len(input.dims)
        axes = tuple(a % ndim for a in (axes or (ndim - 1,)))
        params = {
            "axes": axes,
            "elementwise_affine": elementwise_affine,
            "eps": eps,
            "initializers": [ConstantInitializer(1.0), None]
            if elementwise_affine
            else None,
        }
        return self._add(OperatorType.LAYERNORM, "layer_norm", [input], params, name)[0]

    def embedding(
        self,
        input: Tensor,
        num_entries: int,
        out_dim: int,
        aggr: AggrMode = AggrMode.NONE,
        dtype: DataType = DataType.FLOAT,
        kernel_initializer=None,
        name: Optional[str] = None,
    ) -> Tensor:
        params = {
            "num_entries": num_entries,
            "out_dim": out_dim,
            "aggr": aggr,
            "dtype": dtype,
            "initializers": [kernel_initializer],
        }
        return self._add(OperatorType.EMBEDDING, "embedding", [input], params, name)[0]

    def multihead_attention(
        self,
        query: Tensor,
        key: Tensor,
        value: Tensor,
        embed_dim: int,
        num_heads: int,
        kdim: int = 0,
        vdim: int = 0,
        dropout: float = 0.0,
        bias: bool = True,
        causal: bool = False,
        seq_parallel: str = "auto",
        name: Optional[str] = None,
    ) -> Tensor:
        params = {
            "embed_dim": embed_dim,
            "num_heads": num_heads,
            "kdim": kdim or embed_dim,
            "vdim": vdim or embed_dim,
            "dropout": dropout,
            "bias": bias,
            "causal": causal,
            "seq_parallel": seq_parallel,
            # 4 projection kernels (Glorot default) + optional 4 zero biases
            "initializers": [None] * 4
            + ([ZeroInitializer()] * 4 if bias else []),
        }
        return self._add(
            OperatorType.MULTIHEAD_ATTENTION,
            "multihead_attention",
            [query, key, value],
            params,
            name,
        )[0]

    def dropout(self, input: Tensor, rate: float = 0.5, seed: int = 0, name=None):
        return self._add(
            OperatorType.DROPOUT, "dropout", [input], {"rate": rate, "seed": seed}, name
        )[0]

    # element-wise unary
    def _unary(self, op, base, input, params=None, name=None):
        return self._add(op, base, [input], params or {}, name)[0]

    def relu(self, x, name=None):
        return self._unary(OperatorType.RELU, "relu", x, None, name)

    def sigmoid(self, x, name=None):
        return self._unary(OperatorType.SIGMOID, "sigmoid", x, None, name)

    def tanh(self, x, name=None):
        return self._unary(OperatorType.TANH, "tanh", x, None, name)

    def elu(self, x, name=None):
        return self._unary(OperatorType.ELU, "elu", x, None, name)

    def gelu(self, x, name=None):
        return self._unary(OperatorType.GELU, "gelu", x, None, name)

    def identity(self, x, name=None):
        return self._unary(OperatorType.IDENTITY, "identity", x, None, name)

    def exp(self, x, name=None):
        return self._unary(OperatorType.EXP, "exp", x, None, name)

    def sin(self, x, name=None):
        return self._unary(OperatorType.SIN, "sin", x, None, name)

    def cos(self, x, name=None):
        return self._unary(OperatorType.COS, "cos", x, None, name)

    def pow(self, x, exponent: float, name=None):
        return self._unary(
            OperatorType.POW, "pow", x, {"exponent": exponent}, name
        )

    def rsqrt(self, x, name=None):
        return self._unary(OperatorType.RSQRT, "rsqrt", x, None, name)

    def scalar_multiply(self, x, scalar: float, name=None):
        return self._unary(
            OperatorType.SCALAR_MULTIPLY, "scalar_multiply", x, {"scalar": scalar}, name
        )

    def scalar_add(self, x, scalar: float, name=None):
        return self._unary(
            OperatorType.SCALAR_ADD, "scalar_add", x, {"scalar": scalar}, name
        )

    def scalar_sub(self, x, scalar: float, name=None):
        return self._unary(
            OperatorType.SCALAR_SUB, "scalar_sub", x, {"scalar": scalar}, name
        )

    def scalar_true_divide(self, x, scalar: float, name=None):
        return self._unary(
            OperatorType.SCALAR_TRUE_DIV, "scalar_true_div", x, {"scalar": scalar}, name
        )

    # element-wise binary
    def _binary(self, op, base, a, b, name=None):
        return self._add(op, base, [a, b], {}, name)[0]

    def add(self, a, b, name=None):
        return self._binary(OperatorType.EW_ADD, "add", a, b, name)

    def subtract(self, a, b, name=None):
        return self._binary(OperatorType.EW_SUB, "subtract", a, b, name)

    def multiply(self, a, b, name=None):
        return self._binary(OperatorType.EW_MUL, "multiply", a, b, name)

    def divide(self, a, b, name=None):
        return self._binary(OperatorType.EW_DIV, "divide", a, b, name)

    def max(self, a, b, name=None):
        return self._binary(OperatorType.EW_MAX, "max", a, b, name)

    def min(self, a, b, name=None):
        return self._binary(OperatorType.EW_MIN, "min", a, b, name)

    def batch_matmul(
        self, a: Tensor, b: Tensor, a_seq_length_dim=-1, b_seq_length_dim=-1, name=None
    ):
        params = {
            "a_seq_length_dim": a_seq_length_dim,
            "b_seq_length_dim": b_seq_length_dim,
        }
        return self._add(OperatorType.BATCHMATMUL, "batch_matmul", [a, b], params, name)[0]

    def softmax(self, input: Tensor, dim: int = -1, name=None):
        return self._add(OperatorType.SOFTMAX, "softmax", [input], {"dim": dim}, name)[0]

    def concat(self, tensors: Sequence[Tensor], axis: int, name=None):
        return self._add(OperatorType.CONCAT, "concat", list(tensors), {"axis": axis}, name)[0]

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]], axis: int, name=None):
        if isinstance(sizes, int):
            total = input.dims[axis]
            sizes = [total // sizes] * sizes
        outs = self._add(
            OperatorType.SPLIT, "split", [input], {"axis": axis, "sizes": tuple(sizes)}, name
        )
        return outs

    def reshape(self, input: Tensor, shape: Sequence[int], name=None):
        return self._add(
            OperatorType.RESHAPE, "reshape", [input], {"shape": tuple(shape)}, name
        )[0]

    def transpose(self, input: Tensor, perm: Sequence[int], name=None):
        return self._add(
            OperatorType.TRANSPOSE, "transpose", [input], {"perm": tuple(perm)}, name
        )[0]

    def reverse(self, input: Tensor, axis: int, name=None):
        return self._add(OperatorType.REVERSE, "reverse", [input], {"axis": axis}, name)[0]

    def flat(self, input: Tensor, name=None):
        return self._add(OperatorType.FLAT, "flat", [input], {}, name)[0]

    def cast(self, input: Tensor, dtype: DataType, name=None):
        return self._add(OperatorType.CAST, "cast", [input], {"dtype": dtype}, name)[0]

    def reduce_sum(self, input: Tensor, axes: Sequence[int], keepdims=False, name=None):
        return self._add(
            OperatorType.REDUCE_SUM,
            "reduce_sum",
            [input],
            {"axes": tuple(axes), "keepdims": keepdims},
            name,
        )[0]

    def mean(self, input: Tensor, axes: Sequence[int], keepdims=False, name=None):
        return self._add(
            OperatorType.MEAN, "mean", [input], {"axes": tuple(axes), "keepdims": keepdims}, name
        )[0]

    # parallel ops (reference: FFModel::create_combine/repartition/replicate/
    # reduction builder surface; src/parallel_ops/)
    def repartition(self, input: Tensor, axis: int, degree: int, parallel_idx: int = -1, name=None):
        return self._add(
            OperatorType.REPARTITION,
            "repartition",
            [input],
            {"axis": axis, "degree": degree, "parallel_idx": parallel_idx},
            name,
        )[0]

    def combine(self, input: Tensor, axis: int, degree: int, name=None):
        return self._add(
            OperatorType.COMBINE, "combine", [input], {"axis": axis, "degree": degree}, name
        )[0]

    def replicate(self, input: Tensor, degree: int, parallel_idx: int = -1, name=None):
        return self._add(
            OperatorType.REPLICATE,
            "replicate",
            [input],
            {"degree": degree, "parallel_idx": parallel_idx},
            name,
        )[0]

    def reduction(self, input: Tensor, degree: int, name=None):
        return self._add(
            OperatorType.REDUCTION, "reduction", [input], {"degree": degree}, name
        )[0]

    def pipeline(
        self,
        input: Tensor,
        num_stages: int,
        num_microbatches: int = 4,
        name=None,
    ):
        """Stage-boundary MARKER, pass-through in the PCG executor (the
        reference declares OP_PIPELINE but never implements it either,
        ffconst.h:151). Pipelined execution lives in
        flexflow_tpu.parallel.pipeline.pipeline_apply; compile() warns when
        markers are present so the inert path is never silent."""
        return self._add(
            OperatorType.PIPELINE,
            "pipeline",
            [input],
            {"num_stages": num_stages, "num_microbatches": num_microbatches},
            name,
        )[0]

    def all_to_all(self, input: Tensor, src_axis: int, dst_axis: int, name=None):
        return self._add(
            OperatorType.ALLTOALL,
            "all_to_all",
            [input],
            {"src_axis": src_axis, "dst_axis": dst_axis},
            name,
        )[0]

    # MoE family (reference: model.h:417-439, 487-492)
    def top_k(self, input: Tensor, k: int, sorted: bool = True, name=None):
        return self._add(
            OperatorType.TOPK, "topk", [input], {"k": k, "sorted": sorted}, name
        )

    def group_by(
        self,
        data: Tensor,
        assign: Tensor,
        n: int,
        alpha: float = 1.0,
        stacked: bool = False,
        name=None,
    ):
        out = self._add(
            OperatorType.GROUP_BY,
            "group_by",
            [data, assign],
            {"n": n, "alpha": alpha, "stacked": stacked},
            name,
        )
        return out[0] if stacked else out

    def expert_ffn(self, stacked: Tensor, hidden: int, name=None):
        """Batched per-expert 2-layer MLP on a stacked [n, cap, d] tensor;
        the expert dim shards over the mesh (GShard-style EP — TPU-native,
        no reference counterpart: its experts are separate Linear ops)."""
        return self._add(
            OperatorType.EXPERT_FFN,
            "expert_ffn",
            [stacked],
            {"hidden": hidden},
            name,
        )[0]

    def aggregate(
        self,
        gate_values: Tensor,
        gate_assign: Tensor,
        exp_preds,
        n: int,
        lambda_bal: float = 0.0,
        name=None,
    ):
        stacked = isinstance(exp_preds, Tensor)
        preds = [exp_preds] if stacked else list(exp_preds)
        return self._add(
            OperatorType.AGGREGATE,
            "aggregate",
            [gate_values, gate_assign] + preds,
            {"n": n, "lambda_bal": lambda_bal, "stacked": stacked},
            name,
        )[0]

    def aggregate_spec(
        self,
        gate_values: Tensor,
        gate_assign: Tensor,
        exp_preds,
        n: int,
        lambda_bal: float = 0.0,
        name=None,
    ):
        """Speculative aggregate: expert outputs combine like aggregate()
        but the gate network receives no gradient (reference:
        src/ops/aggregate_spec.cc)."""
        stacked = isinstance(exp_preds, Tensor)
        preds = [exp_preds] if stacked else list(exp_preds)
        return self._add(
            OperatorType.AGGREGATE_SPEC,
            "aggregate_spec",
            [gate_values, gate_assign] + preds,
            {"n": n, "lambda_bal": lambda_bal, "stacked": stacked},
            name,
        )[0]

    def cache(
        self,
        input: Tensor,
        num_batches: int = 1,
        score_f=None,
        name=None,
    ) -> Tensor:
        """Activation memoization (reference: FFModel::cache, src/ops/
        cache.cc): keeps the last `num_batches` values of `input` on the
        host and scores fresh-vs-cached drift with `score_f(cached_list,
        fresh) -> float` each training step. Read the rolling score with
        `cache_score(name)` — the moe.cc:65-99 pattern feeds it to
        recompile_on_condition to trigger expert re-sharding."""
        out = self._add(
            OperatorType.CACHE,
            "cache",
            [input],
            {"num_batches": int(num_batches)},
            name,
        )[0]
        node = self.graph.nodes[out.ref.guid]
        if score_f is None:
            from flexflow_tpu.ops.moe import default_cache_score

            score_f = default_cache_score
        self._cache_specs[node.name] = (int(num_batches), score_f)
        return out

    def cache_score(self, name: str) -> float:
        """Latest drift score of a cache op (1.0 until enough batches)."""
        return self._cache_scores.get(name, 1.0)

    def _update_cache(self, name: str, fresh) -> None:
        spec = self._cache_specs.get(name)
        if spec is None:
            return
        num_batches, score_f = spec
        state = self._cache_state.setdefault(name, [])
        if len(state) >= num_batches:
            self._cache_scores[name] = float(score_f(list(state), fresh))
        state.append(fresh)
        del state[: max(0, len(state) - num_batches)]

    def moe(
        self,
        input: Tensor,
        num_exp: int,
        num_select: int,
        expert_hidden_size: int,
        alpha: float = 2.0,
        lambda_bal: float = 0.0,
        batched: bool = False,
    ) -> Tensor:
        """MoE sugar (reference: FFModel::moe, model.h:487-492): gate network
        → topk → group_by → experts → aggregate. batched=True uses ONE
        stacked ExpertFFN whose expert dim can shard over the mesh
        (expert parallelism); False mirrors the reference's per-expert
        Linear ops."""
        gate = self.dense(input, num_exp, name=None)
        gate = self.softmax(gate)
        values, assign = self.top_k(gate, num_select)
        if batched:
            stacked = self.group_by(input, assign, num_exp, alpha, stacked=True)
            preds = self.expert_ffn(stacked, expert_hidden_size)
            return self.aggregate(values, assign, preds, num_exp, lambda_bal)
        grouped = self.group_by(input, assign, num_exp, alpha)
        exp_preds = [
            self.dense(
                self.dense(g, expert_hidden_size, activation=ActiMode.RELU),
                expert_hidden_size,
            )
            for g in grouped
        ]
        return self.aggregate(values, assign, exp_preds, num_exp, lambda_bal)

    # ------------------------------------------------------------- compile

    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type: LossType = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics: Sequence[MetricsType] = (MetricsType.ACCURACY,),
        comp_mode: CompMode = CompMode.TRAINING,
        logits: Optional[Tensor] = None,
        devices=None,
        strategy=None,
    ):
        """Pick a strategy, propagate parallel shapes, build the executor
        (reference: FFModel::compile, model.cc:2789-3154; SURVEY §3.2).

        strategy: an explicit parallel.strategy.Strategy to use instead of
        the config-driven choice (the reference's --import-strategy path).
        """
        from flexflow_tpu.parallel.strategy import choose_strategy

        if any(
            n.op_type == OperatorType.PIPELINE for n in self.graph.nodes.values()
        ):
            import warnings

            warnings.warn(
                "PIPELINE markers are pass-through in the PCG executor; for "
                "pipelined execution use flexflow_tpu.parallel.pipeline."
                "pipeline_apply (GPipe over a 'pipe' mesh axis).",
                stacklevel=2,
            )
        # a pre-assigned `ffmodel.optimizer = ...` survives a compile()
        # without an optimizer argument (reference native-python idiom,
        # flexflow_cffi.py — examples/python/pytorch/mnist_mlp.py sets the
        # attribute then calls compile(loss_type=..., metrics=...))
        self.optimizer = optimizer or self.optimizer or SGDOptimizer(
            lr=self.config.learning_rate,
            weight_decay=self.config.weight_decay,
        )
        self.loss_type = loss_type
        self.metric_types = tuple(metrics)

        # measured flash-kernel tile sizes from the calibration table
        # (scripts/calibrate.py --tune-flash) replace the built-in
        # defaults for every attention lowering this compile produces
        if self.config.calibration_file:
            import json as _json
            import os as _os

            if _os.path.exists(self.config.calibration_file):
                try:
                    with open(self.config.calibration_file) as f:
                        _doc = _json.load(f)
                except (OSError, ValueError):
                    _doc = {}
                fb = _doc.get("flash_blocks") or {}
                if fb.get("block_q") and fb.get("block_k"):
                    from flexflow_tpu.ops.pallas.flash_kernel import (
                        set_tuned_blocks,
                    )

                    set_tuned_blocks(fb["block_q"], fb["block_k"])
                db = _doc.get("decode_blocks") or {}
                if db.get("block_k"):
                    from flexflow_tpu.ops.pallas.decode_kernel import (
                        set_tuned_decode_blocks,
                    )

                    set_tuned_decode_blocks(db["block_k"])
                caps = _doc.get("attn_caps") or {}
                if caps.get("mono_mb") and caps.get("chunk_mb"):
                    from flexflow_tpu.ops.attention import set_dense_caps

                    set_dense_caps(caps["mono_mb"], caps["chunk_mb"])

        if logits is None:
            sinks = self.graph.sinks()
            if len(sinks) != 1:
                raise ValueError(
                    "model has multiple sinks; pass logits= to compile()"
                )
            logits = Tensor(self, TensorRef(sinks[0], 0))
        self._logits = logits

        devices = jax.devices() if devices is None else list(devices)
        # pristine builder graph + the caller's compile arguments, restored
        # by the recompile hook so a recompile keeps the user's explicit
        # strategy/devices (reference: RecompileState, recompile.h:26-41)
        self._prestrategy_graph = self.graph.copy()
        self._builder_logits_ref = logits.ref  # pre-substitution identity
        self._compile_devices = devices
        self._compile_strategy = strategy
        self.strategy = strategy or choose_strategy(self, len(devices))
        self.strategy.apply(self.graph)
        propagate_shapes(self.graph)

        # fold adjacent parallel-op chains into FusedParallelOp nodes
        # (reference: fused_parallel_op.cc; enabled with the fusion pass)
        if (
            self.config.perform_fusion
            and getattr(self.strategy, "pipeline", None) is None
        ):
            from flexflow_tpu.parallel.parallel_ops import fold_parallel_ops

            if fold_parallel_ops(self.graph):
                propagate_shapes(self.graph)

        # substitution optimization pass (reference: base_optimize inside
        # GraphSearchHelper::graph_optimize — a core compile phase; the
        # bundled default rules run unless --no-substitution, SURVEY §2.5).
        # A pipelined strategy pins the trunk's guids
        # (PipelineSpec.structure), so graph-rewriting passes are skipped —
        # rewritten guids would dangle in the block template.
        pipelined = getattr(self.strategy, "pipeline", None) is not None
        subst_requested = (
            self.config.enable_substitution
            or self.config.substitution_json
            or self.config.perform_fusion
        )
        if pipelined and (
            self.config.substitution_json or self.config.perform_fusion
        ):
            import warnings

            warnings.warn(
                "substitution/fusion passes are skipped under a pipelined "
                "strategy (the block template pins pre-rewrite node ids)",
                stacklevel=2,
            )
        if not pipelined and subst_requested:
            from flexflow_tpu.search.substitution import apply_substitution_pass

            self.graph, new_ref = apply_substitution_pass(
                self.graph, logits.ref, self.config, self.strategy.mesh_config
            )
            logits = Tensor(self, new_ref)
            self._logits = logits

        # FusedOp pass (reference: apply_fusion, model.cc:2489-2597): fold
        # fusible chains into FUSED nodes; the logits node stays unfused so
        # downstream references (loss, from_logits check) hold.
        if not pipelined and self.config.perform_fusion:
            from flexflow_tpu.runtime.fusion import apply_fusion

            self.graph, fref_map = apply_fusion(
                self.graph, protected={logits.ref.guid}
            )
            if logits.ref in fref_map:
                logits = Tensor(self, fref_map[logits.ref])
                self._logits = logits

        # label tensor matching the final op's batch partitioning
        # (reference: model.cc:3072-3110)
        logits_shape = self.graph.shape_of(logits.ref)
        batch_dims = [
            d for d in logits_shape.dims if not d.is_replica_dim
        ]
        if loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
            label_dims = tuple(batch_dims[:-1])
            label_dtype = DataType.INT32
        else:
            label_dims = tuple(batch_dims)
            label_dtype = DataType.FLOAT
        label_shape = ParallelTensorShape(label_dims, label_dtype)

        aux = []
        lam_nodes = [
            n
            for n in self.graph.nodes.values()
            if n.op_type
            in (OperatorType.AGGREGATE, OperatorType.AGGREGATE_SPEC)
            and n.params.get("lambda_bal", 0.0) > 0.0
        ]
        if lam_nodes:
            from flexflow_tpu.ops.moe import load_balance_loss

            def moe_aux(values, batch, _nodes=lam_nodes):
                # the balance loss needs the FULL gate distribution [b, n],
                # not the top-k values the aggregate consumes (reference
                # feeds gate_preds into aggregate for exactly this,
                # moe.cc); walk back through the TopK producer.
                total = 0.0
                for n in _nodes:
                    gate_ref, assign_ref = n.inputs[0], n.inputs[1]
                    src = self.graph.nodes[gate_ref.guid]
                    if src.op_type == OperatorType.TOPK:
                        full_ref = src.inputs[0]
                    else:
                        full_ref = gate_ref
                    gp = values[(full_ref.guid, full_ref.out_idx)]
                    asg = values[(assign_ref.guid, assign_ref.out_idx)]
                    total = total + n.params["lambda_bal"] * load_balance_loss(
                        gp, asg, n.params["n"]
                    )
                return total

            aux.append(moe_aux)

        logits_node = self.graph.nodes[logits.ref.guid]
        if logits_node.op_type == OperatorType.FUSED:
            from_logits = (
                logits_node.params["sub_ops"][-1]["op_type"]
                != OperatorType.SOFTMAX
            )
        else:
            from_logits = logits_node.op_type != OperatorType.SOFTMAX
        # strategy validation (analysis/strategy_check.py): re-derive
        # every constraint the lowering relies on — mesh axes exist,
        # degrees are expressible, machine bounds hold — and raise ONE
        # typed StrategyValidationError BEFORE any XLA work, instead of
        # an opaque ValueError from deep inside partition_spec during
        # executor construction. Pipelined strategies lower block
        # weights through their own stacked path, so their findings are
        # informational only.
        from flexflow_tpu.analysis.strategy_check import (
            StrategyValidationError,
            validate_graph_strategy,
        )

        self.strategy_diagnostics = validate_graph_strategy(
            self.graph,
            self.strategy.mesh_config,
            num_devices=len(devices),
        )
        if getattr(self.strategy, "pipeline", None) is None:
            _strategy_errors = [
                d for d in self.strategy_diagnostics if d.severity == "error"
            ]
            if _strategy_errors:
                raise StrategyValidationError(_strategy_errors)

        executor_cls = Executor
        executor_kwargs = {}
        if getattr(self.strategy, "pipeline", None) is not None:
            from flexflow_tpu.runtime.pipeline_executor import (
                PipelinedExecutor,
            )

            pspec = self.strategy.pipeline
            dp = dict(
                zip(
                    self.strategy.mesh_config.axis_names,
                    self.strategy.mesh_config.axis_sizes,
                )
            ).get("data", 1)
            pspec.validate(self.config.batch_size // max(1, dp))
            executor_cls = PipelinedExecutor
            executor_kwargs["pipeline_spec"] = pspec
        self.executor = executor_cls(
            self.graph,
            self.strategy.mesh_config,
            logits.ref,
            label_shape=label_shape,
            loss_type=loss_type,
            metrics=self.metric_types,
            optimizer=self.optimizer,
            devices=devices,
            aux_loss_fns=aux,
            logits_from_logits=from_logits,
            mixed_precision=self.config.allow_mixed_precision,
            seq_length=self.config.seq_length,
            # the GPipe executor has its own forward path; sparse table
            # updates ride the plain executor only
            sparse_embedding_update=(
                self.config.sparse_embedding_update
                and executor_cls is Executor
            ),
            **executor_kwargs,
        )
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.executor.init_params(init_key)
        self.opt_state = self.optimizer.init_state(self.params)

        if self.config.computation_graph_file or self.config.task_graph_file:
            # cost the artifacts with the SAME machine description the
            # search uses (--chip / --machine-model-*), not defaults
            from flexflow_tpu.core.machine import MachineSpec
            from flexflow_tpu.search.machine_model import build_machine_model

            spec = MachineSpec(
                num_nodes=max(1, self.config.num_nodes),
                chips_per_node=max(
                    1, len(devices) // max(1, self.config.num_nodes)
                ),
                chip=self.config.chip,
            )
            mm = build_machine_model(self.config, spec)
        if self.config.computation_graph_file:
            from flexflow_tpu.utils.dot import export_pcg_dot

            export_pcg_dot(
                self.graph,
                self.config.computation_graph_file,
                include_costs=self.config.include_costs_dot_graph,
                spec=spec,
                machine_model=mm,
            )
        if self.config.task_graph_file:
            from flexflow_tpu.utils.dot import export_task_graph_dot

            export_task_graph_dot(
                self.graph,
                self.config.task_graph_file,
                self.strategy.mesh_config.axis_sizes,
                spec=spec,
                machine_model=mm,
            )

    # ------------------------------------------------------------- training

    def fit(
        self,
        x: Union[Dict[str, np.ndarray], Sequence[np.ndarray], np.ndarray],
        y: np.ndarray,
        epochs: Optional[int] = None,
        batch_size: Optional[int] = None,
        shuffle: bool = False,
        verbose: bool = True,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        callbacks=None,
        telemetry=None,
    ):
        """Training loop (reference: flexflow_cffi.py:1916-1958 fit —
        per-iter begin_trace; next_batch; forward; zero_gradients; backward;
        update; end_trace. Here one jitted step does all of it). Callback
        hooks follow the reference keras loop (base_model.py:374-430):
        set_model, on_train_begin, per-epoch and per-batch hooks; a True
        return from on_epoch_end stops training early.

        telemetry: a flexflow_tpu.telemetry.Telemetry bundle, or None to
        build one from the config's --metrics-out/--metrics-jsonl/
        --trace knobs (the serving flags now drive training too). With
        the bundle attached, fit exports per-iteration train_* series
        (step time, examples/s, loss, recompiles, jit-cache builds) and
        a Chrome trace of iteration/epoch spans; the hot loop pays one
        predicate branch plus two appends per iteration — losses and
        rows are materialized at epoch end, AFTER the existing
        block_until_ready, so telemetry adds no device syncs."""
        if self.executor is None:
            raise RuntimeError("call compile() before fit()")
        epochs = epochs or self.config.epochs
        batch_size = batch_size or self.config.batch_size
        callbacks = list(callbacks or [])
        tele = telemetry
        if tele is None:
            from flexflow_tpu.telemetry import build_telemetry

            tele = build_telemetry(self.config)
        self._telemetry = tele
        train_iters = 0  # global iteration counter across epochs
        for cb in callbacks:
            # the keras frontend pre-binds its own Model wrapper; direct
            # FFModel.fit users get the FFModel itself
            if getattr(cb, "model", None) is None:
                cb.set_model(self)
        for cb in callbacks:
            cb.on_train_begin()

        arrays = self._pack_dataset(x, y)
        loader = SingleDataLoader(arrays, batch_size, shuffle=shuffle)
        step = self.executor.train_step()

        history = []
        warm = False
        early_stop = False
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            # a LearningRateScheduler rebinds the optimizer and drops the
            # cached jitted step; re-fetch so the new LR takes effect
            if callbacks:
                step = self.executor.train_step()
            perf = PerfMetrics()
            loader.reset()
            t0 = time.perf_counter()
            epoch_t0 = t0
            samples = 0
            step_results = []  # device arrays; converted once per epoch so
            # the loop stays async (no per-iteration host sync)
            stamps = []  # host clock at each dispatch (telemetry only)
            sample_counts = []
            for it in range(loader.num_batches):
                for cb in callbacks:
                    cb.on_batch_begin(it)
                np_batch = loader.next_batch()
                batch = self.executor.shard_batch(np_batch)
                self._rng, key = jax.random.split(self._rng)
                self.params, self.opt_state, loss, mets = step(
                    self.params, self.opt_state, batch, key
                )
                if tele is not None:
                    # dispatch-to-dispatch host stamps; rows/spans are
                    # built at epoch end, off the hot loop
                    stamps.append(time.perf_counter())
                    sample_counts.append(
                        len(next(iter(np_batch.values())))
                    )
                if self._cache_specs:
                    # surface cache-op inputs to the host memoizer
                    # (syncs; only models that built cache() ops pay it)
                    mets = dict(mets)
                    for mname in [
                        k for k in mets if k.startswith("__cache_")
                    ]:
                        self._update_cache(
                            mname[len("__cache_"):],
                            np.asarray(mets.pop(mname)),
                        )
                if not warm:
                    # exclude compile time from throughput (the reference's
                    # timing also starts after warmup, alexnet.cc:125-135)
                    jax.block_until_ready(loss)
                    t0 = time.perf_counter()
                    warm = True
                else:
                    samples += len(next(iter(np_batch.values())))
                step_results.append((loss, mets))
                for cb in callbacks:
                    cb.on_batch_end(it)
                pf = self.config.print_freq
                if verbose and pf > 0 and (it + 1) % pf == 0:
                    # reference: metrics printed every printFreq iterations
                    # (model.cc printFreq); float() syncs, so only paid on
                    # the requested cadence
                    print(
                        f"iter {it + 1}/{loader.num_batches}: "
                        f"loss = {float(loss):.4f}"
                    )
            jax.block_until_ready(self.params)
            elapsed = time.perf_counter() - t0
            losses = []
            for loss, mets in step_results:
                fl = float(loss)
                perf.update(jax.tree_util.tree_map(float, mets), fl)
                losses.append(fl)
            self._perf_metrics = perf
            thpt = samples / elapsed if elapsed > 0 else 0.0
            if tele is not None:
                train_iters = self._record_training_epoch(
                    tele, epoch, epoch_t0, stamps, sample_counts, losses,
                    train_iters,
                )
            history.append({"epoch": epoch, "throughput": thpt, **perf.__dict__})
            if verbose:
                print(f"epoch {epoch}: {perf.report()}")
                print(f"THROUGHPUT = {thpt:.2f} samples/s")
            if checkpoint_dir and (epoch + 1) % max(1, checkpoint_every) == 0:
                self.save_checkpoint(checkpoint_dir, step=epoch)
            for cb in callbacks:
                if cb.on_epoch_end(epoch) is True:
                    # reference: base_model.py:423-428 — accuracy target
                    # reached, stop early
                    if verbose:
                        print(
                            "Accuracy reaches, now early stop, "
                            f"epoch: {epoch}"
                        )
                    early_stop = True
            if early_stop:
                break
        for cb in callbacks:
            cb.on_train_end()
        if tele is not None:
            tele.flush()
        return history

    def _record_training_epoch(
        self, tele, epoch, epoch_t0, stamps, sample_counts, losses,
        train_iters,
    ) -> int:
        """Materialize one epoch's telemetry AFTER the epoch-end device
        sync: per-iteration train_* gauges + counters, one JSONL sample
        row per iteration, iteration/epoch spans on the trace, and the
        recompile/jit-cache mirrors. Returns the advanced global
        iteration counter. Registry handles are get-or-create dict
        lookups — cheap at epoch granularity."""
        reg = tele.registry
        g_loss = reg.gauge("train_loss", help="training loss (last step)")
        g_step = reg.gauge(
            "train_step_time_s",
            help="per-iteration wall time, host dispatch-to-dispatch",
        )
        g_eps = reg.gauge(
            "train_examples_per_s",
            help="instantaneous examples/s of the last iteration",
        )
        g_epoch = reg.gauge("train_epoch", help="current epoch index")
        c_iters = reg.counter(
            "train_iterations_total", help="training iterations run"
        )
        c_examples = reg.counter(
            "train_examples_total", help="training examples consumed"
        )
        c_recompiles = reg.counter(
            "train_recompiles_total",
            help="recompile_on_condition fires (model mutations)",
        )
        g_jit = reg.gauge(
            "train_jit_builds",
            help="step callables built by the executor "
            "(each first call is one XLA compile)",
        )
        g_inval = reg.gauge(
            "train_jit_invalidations",
            help="cached step callables dropped (seq-length change, "
            "LR rebind)",
        )
        tracer = tele.tracer
        g_epoch.set(epoch)
        prev = epoch_t0
        for i, t_end in enumerate(stamps):
            fl = losses[i] if i < len(losses) else float("nan")
            dt = t_end - prev
            g_loss.set(fl)
            g_step.set(dt)
            g_eps.set(sample_counts[i] / dt if dt > 0 else 0.0)
            c_iters.inc()
            c_examples.inc(sample_counts[i])
            c_recompiles.set_monotonic(float(self.recompile_events))
            g_jit.set(float(self.executor.jit_builds))
            g_inval.set(float(self.executor.jit_invalidations))
            tracer.complete(
                "iteration", "train", prev, t_end,
                args={"epoch": epoch, "iteration": train_iters,
                      "loss": fl},
            )
            tele.sample(train_iters)
            prev = t_end
            train_iters += 1
        tracer.complete(
            "epoch", "train", epoch_t0, prev if stamps else epoch_t0,
            args={"epoch": epoch},
        )
        return train_iters

    def evaluate(self, x, y, batch_size: Optional[int] = None, callbacks=None):
        batch_size = batch_size or self.config.batch_size
        callbacks = list(callbacks or [])
        for cb in callbacks:
            if getattr(cb, "model", None) is None:
                cb.set_model(self)
        for cb in callbacks:
            cb.on_train_begin()
        arrays = self._pack_dataset(x, y)
        loader = SingleDataLoader(arrays, batch_size)
        estep = self.executor.eval_step()
        perf = PerfMetrics()
        for it, batch in enumerate(loader):
            for cb in callbacks:
                cb.on_batch_begin(it)
            b = self.executor.shard_batch(batch)
            loss, mets = estep(self.params, b)
            perf.update(jax.tree_util.tree_map(float, mets), float(loss))
            for cb in callbacks:
                cb.on_batch_end(it)
        self._perf_metrics = perf
        for cb in callbacks:
            cb.on_train_end()
        return perf

    def get_perf_metrics(self) -> PerfMetrics:
        """Most recent epoch's accumulated metrics (reference:
        FFModel::get_perf_metrics via flexflow_model_get_perf_metrics —
        the handle VerifyMetrics callbacks read, flexflow_cffi.py:2221)."""
        perf = getattr(self, "_perf_metrics", None)
        return perf if perf is not None else PerfMetrics()

    def _pack_dataset(self, x, y) -> Dict[str, np.ndarray]:
        # reference native-python scripts pass the handles returned by
        # create_data_loader (flexflow_cffi.py fit(x=dataloader_input,
        # y=dataloader_label)); unwrap them to the named arrays
        if isinstance(x, TensorDataLoader):
            x = {x.name: x.array}
        elif isinstance(x, (list, tuple)) and any(
            isinstance(v, TensorDataLoader) for v in x
        ):
            if not all(isinstance(v, TensorDataLoader) for v in x):
                raise TypeError(
                    "fit(x=[...]) mixes create_data_loader handles with "
                    "raw arrays; pass all loaders or all arrays"
                )
            x = {v.name: v.array for v in x}
        if isinstance(y, TensorDataLoader):
            y = y.array
        if isinstance(x, dict):
            arrays = dict(x)
        else:
            xs = list(x) if isinstance(x, (list, tuple)) else [x]
            if len(xs) != len(self._input_order):
                raise ValueError(
                    f"model has {len(self._input_order)} inputs, got {len(xs)}"
                )
            arrays = dict(zip(self._input_order, xs))
        # coerce each input to its declared dtype (embedding ids arriving
        # as floats from generic loaders / the C ABI's single float
        # buffer, flexflow_c.h fit)
        if self.executor is not None:
            shapes = self.executor.input_shapes()
            for name, arr in arrays.items():
                want = shapes.get(name)
                if want is None or want.dtype.value not in (
                    "float32", "int32", "int64", "float64", "bool",
                ):
                    continue  # bf16/f16 inputs: numpy has no such dtype
                np_dt = np.dtype(want.dtype.value)
                if getattr(arr, "dtype", None) != np_dt:
                    arrays[name] = np.asarray(arr).astype(np_dt)
        arrays["label"] = y
        return arrays

    # reference native-python dataloader surface (flexflow_cffi.py:2050
    # create_data_loader → SingleDataLoader; the compat namespace's
    # examples pass these handles straight into fit/evaluate)
    def create_data_loader(self, tensor, array) -> "TensorDataLoader":
        """reference: FFModel.create_data_loader(batch_tensor, numpy) —
        binds a full dataset array to one input tensor; None (the
        label_tensor handle) binds the label slot."""
        if tensor is None:
            return TensorDataLoader("label", array)
        node = (
            self.graph.nodes.get(tensor.ref.guid)
            if getattr(tensor, "ref", None) is not None
            else None
        )
        if node is None or node.op_type != OperatorType.INPUT:
            raise ValueError(
                "create_data_loader takes an INPUT tensor (or None for "
                f"the label), got {tensor!r}"
            )
        return TensorDataLoader(node.name, array)

    @property
    def label_tensor(self):
        """reference: flexflow_model_get_label_tensor — the label tensor
        created at compile to match the final op's shape; here a named
        handle create_data_loader recognizes."""
        if self.executor is None:
            raise RuntimeError("call compile() before label_tensor")
        return None  # create_data_loader(None, y) binds the label slot

    def init_layers(self):
        """reference spelling of init_operators (flexflow_cffi.py)."""
        return self.init_operators()

    # compat verbs (reference training loop: forward/zero_gradients/backward/
    # update — subsumed by the fused jitted step; provided for ported scripts)
    def forward(self, batch: Dict[str, np.ndarray], seq_length: Optional[int] = None):
        """reference: FFModel::forward(seq_length), model.cc:2409 — the
        optional per-iteration sequence truncation reaches BatchMatmul.
        Like the reference (default -1 = full), the truncation applies to
        THIS call only; omitting seq_length restores the config default."""
        self.executor.set_seq_length(
            seq_length if seq_length is not None else self.config.seq_length
        )
        b = self.executor.shard_batch(batch)
        return self.executor.forward_fn()(self.params, b)

    def generate(
        self,
        prompts,
        max_new_tokens: int = 16,
        serve_config=None,
        eos_token=None,
        draft_model=None,
    ):
        """Autoregressive generation with continuous batching (the
        FlexFlow Serve surface grafted onto the training FFModel): token-id
        prompts in, generated token lists out, scheduled by
        serving.scheduler over a preallocated KV cache. Greedy unless the
        ServeConfig sets a temperature. The model must be compiled, take a
        single int token input, and use causal self-attention.
        `serve_config.spec_draft` turns on speculative decoding
        (serving/spec.py); `draft_model` supplies the small draft LM when
        spec_draft is "model"."""
        from flexflow_tpu.serving.api import ServeConfig, generate

        if self.executor is None:
            raise RuntimeError("call compile() before generate()")
        if serve_config is None:
            serve_config = ServeConfig.from_config(self.config)
        return generate(
            self,
            prompts,
            max_new_tokens=max_new_tokens,
            serve=serve_config,
            eos_token=eos_token,
            draft_model=draft_model,
        )

    def compile_for_serving(
        self,
        serve_config=None,
        dp: Optional[int] = None,
        tp: Optional[int] = None,
        num_hosts: Optional[int] = None,
        verbose: bool = False,
    ):
        """Apply a (data, model) SERVING mesh to the compiled model —
        head-sharded attention weights and (via kv_cache.from_model) K/V
        pools as NamedShardings on the mesh `serving/distributed.py`
        builds through `runtime/multihost` (outer axis on DCN, inner on
        ICI) — instead of inheriting the training strategy's sharding.

        Mesh selection: explicit `dp`/`tp` args, else the config's
        ``--serve-mesh dp,tp`` flag, else `search_serving_strategy`'s
        winner (which is then recorded as *applied* rather than
        inherited — the explain/export path reports the mesh the engine
        actually executes). ``--serve-hosts`` (or `num_hosts`) sets the
        scheduler's host-partition count; it defaults to the process
        count on real pods and to dp for simulated-host CPU runs.

        Returns the `ServingPlacement`; also stored as
        `self.serving_placement`, where `serving.api.build_scheduler`
        and `KVCache/PagedKVCache.from_model` pick it up."""
        from flexflow_tpu.core.types import OperatorType
        from flexflow_tpu.serving import distributed as dserve

        if self.executor is None:
            raise RuntimeError("call compile() before compile_for_serving()")
        cfg = self.config
        sc = serve_config  # a ServeConfig overrides the FFConfig mirror

        def knob(sc_name, cfg_name, default):
            if sc is not None:
                return getattr(sc, sc_name)
            return getattr(cfg, cfg_name, default)

        source = "flag"
        sr = None
        if dp is None or tp is None:
            spec = dserve.parse_serve_mesh(knob("serve_mesh", "serve_mesh", ""))
            if spec is not None:
                dp, tp = spec
            else:
                from flexflow_tpu.search.auto import search_serving_strategy

                sr = search_serving_strategy(
                    self,
                    batch_size=max(1, knob("max_seqs", "serve_max_seqs", 8)),
                )
                dp, tp = sr.dp, sr.tp
                source = "searched"
        if num_hosts is None:
            num_hosts = knob("serve_hosts", "serve_hosts", 0) or None
        placement = dserve.build_placement(
            self, dp, tp, num_hosts=num_hosts, mesh_source=source
        )

        # cache geometry (the from_model defaults) — validated here so a
        # bad --serve-mesh fails before any device work, and exported in
        # the placement doc for fxlint strategy-validate
        max_seqs = knob("max_seqs", "serve_max_seqs", 8)
        max_seq_len = knob("max_seq_len", "serve_max_seq_len", 256)
        num_pages = None
        if knob("kv_layout", "serve_kv_layout", "paged") == "paged":
            from flexflow_tpu.serving.kv_cache import default_page_size

            page_size = knob(
                "kv_page_size", "serve_kv_page_size", 0
            ) or default_page_size(max_seq_len)
            num_pages = knob("kv_pages", "serve_kv_pages", 0) or (
                max_seqs * max_seq_len // page_size
            )
            placement.validate_geometry(max_seqs, num_pages)

        def _serving_sharding(node, i, wshape):
            if node.op_type == OperatorType.MULTIHEAD_ATTENTION:
                ndim = sum(1 for d in wshape.dims if not d.is_replica_dim)
                if i in (0, 1, 2):  # wq/wk/wv: (embed, heads, head_dim)
                    return placement.head_sharding(1, ndim)
                if i in (3, 4, 5, 6):  # wo / bq/bk/bv: heads-major
                    return placement.head_sharding(0, ndim)
            return placement.replicated()  # bo + every non-attention op

        self.params = self.executor.reshard_params(
            self.params, _serving_sharding
        )
        self.serving_placement = placement
        if sr is not None:
            sr.mesh_execution = "applied"
            self.serve_search_result = sr
            if verbose or cfg.search_explain:
                print(f"[serve-search] {sr.describe()}")
        if verbose or cfg.search_explain:
            print(f"[serve-mesh] {placement.describe()}")
        export = getattr(cfg, "serve_export_strategy", "")
        if export:
            import json

            doc = placement.to_doc(max_seqs=max_seqs, num_pages=num_pages)
            if sr is not None:
                doc["search"] = sr.to_doc()
            with open(export, "w") as f:
                json.dump(doc, f, indent=2)
        return placement

    def zero_gradients(self):
        pass  # gradients are functional; nothing to zero

    def backward(self):
        """reference: FFModel::backward (model.cc:2432). Subsumed: the
        jitted train step computes grads via jax.value_and_grad."""

    def compute_gradients(self, x, y) -> Dict[int, list]:
        """Per-parameter loss gradients for one batch, as host arrays keyed
        like `params` ({guid: [grad per weight slot]}).

        The alignment harness's window into the backward pass (reference:
        align/align_ff_utils.py run_fwd_bwd reads each op's region gradients
        after backward()); here one jax.grad over the whole compiled program
        yields every weight gradient at once. Dropout is disabled
        (train=False) so results are deterministic."""
        if self.executor is None:
            raise RuntimeError("call compile() before compute_gradients()")
        self.executor.set_seq_length(self.config.seq_length)
        batch = self.executor.shard_batch(self._pack_dataset(x, y))
        grads = self.executor.grad_fn()(self.params, batch)
        return {
            guid: [np.asarray(g) for g in gs] for guid, gs in grads.items()
        }

    def update(self):
        """reference: FFModel::update (model.cc:2463). Subsumed: the jitted
        train step applies the optimizer in the same program."""

    def init_operators(self):
        """reference: FFModel::init_operators (model.cc:2403 — per-op INIT
        index tasks allocating OpMeta). Here it AOT-compiles the train step
        on zero-filled example shapes (jit is lazy, so merely building the
        jitted callable would compile nothing) — the first fit() iteration
        then hits the compile cache instead of stalling."""
        if self.executor is None:
            raise RuntimeError("call compile() before init_operators()")
        step = self.executor.train_step()
        zeros = {
            name: np.zeros(
                tuple(d.size for d in shape.dims if not d.is_replica_dim),
                shape.dtype.to_jnp(),  # jnp scalar types are np-compatible
            )
            for name, shape in self.executor.input_shapes().items()
        }
        sharded = self.executor.shard_batch(zeros)
        step.lower(
            self.params, self.opt_state, sharded, jax.random.PRNGKey(0)
        ).compile()

    def begin_trace(self, trace_id: int = 0):
        """reference: runtime->begin_trace (transformer.cc:192 — Legion
        capture-and-replay). Subsumed by jit compilation caching."""

    def end_trace(self, trace_id: int = 0):
        """See begin_trace."""

    def profile_operators(self, batch, iters: int = 5, verbose: bool = True):
        """Per-op forward timing table (reference: --profiling per-kernel
        cudaEvent prints, kernels/linear_kernels.cu:95-117)."""
        from flexflow_tpu.utils.profiling import profile_operators

        return profile_operators(self, batch, iters=iters, verbose=verbose)

    def audit_cost_model(self, batch=None, **kwargs):
        """Predicted-vs-measured cost-model audit (search/audit.py):
        price the compiled graph with the search's own CostModel, time
        the real executor step, export cost_model_error_ratio gauges
        per op family, and feed the residuals back through the
        calibration table's read-merge-write path."""
        from flexflow_tpu.search.audit import audit_cost_model

        return audit_cost_model(self, batch=batch, **kwargs)

    def recompile_on_condition(self, state) -> bool:
        """Mid-training model mutation + recompile (reference:
        FFModel::recompile_on_condition, model.cc:2416-2420; MoE expert
        rebalancing, moe.cc:65-99). See runtime.recompile.RecompileState."""
        from flexflow_tpu.runtime.recompile import recompile_on_condition

        return recompile_on_condition(self, state)

    def _live_guid(self, guid: int) -> int:
        """Resolve a builder-graph guid to the compiled graph. Graph
        rewrites (the default substitution pass, fusion) replace builder
        nodes with fresh guids but thread the original identity through
        params['weight_key'] (substitution.py:_dst_params) — the same key
        the recompile hook restores weights by."""
        if guid in self.graph.nodes:
            return guid
        src = (
            self._prestrategy_graph.nodes.get(guid)
            if getattr(self, "_prestrategy_graph", None) is not None
            else None
        )
        if src is not None:
            key = src.params.get("weight_key", src.name)
            for g, n in self.graph.nodes.items():
                if n.params.get("weight_key", n.name) == key:
                    return g
        raise KeyError(
            f"tensor guid {guid} not in the compiled graph (and no rewrite "
            "carried its weight_key forward)"
        )

    def get_tensor(self, guid: int, idx: int = 0) -> np.ndarray:
        """Pull a weight to host (reference: ParallelTensor get_tensor).
        Pipelined trunks read the one [block] slice of their pipe-sharded
        stack (Executor.get_host_param) — never the whole export view."""
        guid = self._live_guid(guid)
        return np.asarray(
            self.executor.get_host_param(self.params, guid, idx)
        )

    def set_tensor(self, guid: int, idx: int, value: np.ndarray):
        guid = self._live_guid(guid)
        node = self.graph.nodes[guid]
        val = jnp.asarray(value, node.weight_shapes[idx].dtype.to_jnp())
        expect = tuple(
            d.size
            for d in node.weight_shapes[idx].dims
            if not d.is_replica_dim
        )
        if tuple(val.shape) != expect:
            # validate BEFORE any mutation (a stacked [S, ...] write to a
            # pipelined template guid must not silently replace the
            # pipe-sharded stack; use checkpoint restore for bulk loads)
            raise ValueError(
                f"set_tensor for {node.name} expects shape {expect}, "
                f"got {tuple(val.shape)}"
            )
        self.executor.set_host_param(self.params, guid, idx, val)

    # --------------------------------------------------------- checkpointing
    # The reference has no model checkpointing (SURVEY §5); this is the
    # orbax-backed upgrade: params + optimizer state + RNG, step-tagged.

    def save_checkpoint(self, directory: str, step: int, max_to_keep: int = 3):
        """Persist training state under `directory/step_<step>/`."""
        from flexflow_tpu.runtime.checkpoint import CheckpointManager

        if self.executor is None:
            raise RuntimeError("call compile() before save_checkpoint()")
        mgr = CheckpointManager(directory, max_to_keep=max_to_keep)
        mgr.save(
            step,
            {
                # on-disk layout is always per-guid (the pipelined
                # executor unstacks its pipe-sharded trunk), so
                # checkpoints restore across strategies — optimizer
                # state subtrees that mirror params convert the same way
                "params": self.executor.export_host_params(self.params),
                "opt_state": self.executor.export_host_opt_state(
                    self.opt_state
                ),
                "rng": self._rng,
            },
        )

    def set_learning_rate(self, lr: float):
        """Mid-training LR change (reference: SGDOptimizer::set_lr /
        flexflow_sgd_optimizer_set_lr — the LR-decay pattern its examples
        use between epochs). The optimizer dataclass is frozen, so the
        model rebinds a replaced copy and drops the cached jitted step;
        optimizer STATE (momentum, Adam moments) is structure-compatible
        and survives."""
        import dataclasses as _dc

        from flexflow_tpu.runtime.optimizer import AdamOptimizer

        if self.optimizer is None:
            raise RuntimeError("call compile() before set_learning_rate()")
        field = "alpha" if isinstance(self.optimizer, AdamOptimizer) else "lr"
        if getattr(self.optimizer, field) == lr:
            return  # unchanged: keep the cached jitted step (a constant
            # schedule must not retrace every epoch)
        self.optimizer = _dc.replace(self.optimizer, **{field: lr})
        if self.executor is not None:
            self.executor.optimizer = self.optimizer
            if self.executor._train_step is not None:
                self.executor.jit_invalidations += 1
            self.executor._train_step = None

    def restore_checkpoint(self, directory: str, step: Optional[int] = None) -> int:
        """Load training state (latest step by default); returns the step.

        Weights are re-placed with the compiled strategy's shardings, so a
        checkpoint written under one mesh restores correctly under another
        (e.g. train data-parallel, resume with a searched dp×tp strategy).
        """
        from flexflow_tpu.runtime.checkpoint import CheckpointManager

        if self.executor is None:
            raise RuntimeError("call compile() before restore_checkpoint()")
        mgr = CheckpointManager(directory)
        step, state = mgr.restore(step)
        self.params = self.executor.place_params(state["params"])
        # mirror subtrees (momentum/Adam moments) re-place like weights,
        # so stateful optimizers survive cross-strategy restores too
        self.opt_state = self.executor.place_opt_state(state["opt_state"])
        if "rng" in state:
            self._rng = jnp.asarray(state["rng"])
        return step
