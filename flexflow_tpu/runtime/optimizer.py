"""Optimizers: SGD (momentum/nesterov/weight-decay) and Adam.

Re-design of the reference's optimizers (reference: src/runtime/optimizer.cc,
optimizer_kernel.cu:88,196). The reference has two sync modes — PS (gradient
replicas summed on an owner shard) and NCCL (ncclAllReduce then local
update); on TPU gradient synchronization is implicit: the jitted step's
gradients already carry the correct shardings and GSPMD emits psum /
reduce-scatter over ICI where replica groups exist. The update itself is a
pure elementwise function applied shard-wise.

Semantics match the reference kernels exactly:
  SGD   (optimizer_kernel.cu sgd_update): g' = g + wd*w;
        v = momentum*v + g'; w -= lr * (nesterov ? g' + momentum*v : v)
  Adam  (optimizer_kernel.cu adam_update): bias-corrected alpha_t schedule,
        w -= alpha_t * m_hat / (sqrt(v_hat) + eps) with decoupled-style
        wd folded into the gradient (reference applies wd additively).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


class Optimizer:
    def init_state(self, params):
        raise NotImplementedError

    def next_step(self, state):
        """Per-iteration hyper-parameter schedule hook
        (reference: Optimizer::next())."""
        return state

    def update(self, params, grads, state):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class SGDOptimizer(Optimizer):
    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0

    def init_state(self, params):
        if self.momentum == 0.0:
            return {"step": jnp.zeros((), jnp.int32)}
        return {
            "step": jnp.zeros((), jnp.int32),
            "velocity": jax.tree_util.tree_map(jnp.zeros_like, params),
        }

    def update(self, params, grads, state):
        wd = self.weight_decay

        if self.momentum == 0.0:
            def upd(w, g):
                g = g + wd * w
                return (w - self.lr * g).astype(w.dtype)

            new_params = jax.tree_util.tree_map(upd, params, grads)
            return new_params, {"step": state["step"] + 1}

        def upd(w, g, v):
            g = g + wd * w
            v_new = self.momentum * v + g
            step = g + self.momentum * v_new if self.nesterov else v_new
            return (w - self.lr * step).astype(w.dtype), v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_v = treedef.flatten_up_to(state["velocity"])
        outs = [upd(w, g, v) for w, g, v in zip(flat_p, flat_g, flat_v)]
        new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
        new_vel = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
        return new_params, {"step": state["step"] + 1, "velocity": new_vel}


@dataclasses.dataclass(frozen=True)
class AdamOptimizer(Optimizer):
    alpha: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    weight_decay: float = 0.0
    epsilon: float = 1e-8

    def init_state(self, params):
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"step": jnp.zeros((), jnp.int32), "m": zeros(), "v": zeros()}

    def update(self, params, grads, state):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        # reference: Optimizer::next() recomputes alpha_t with bias correction
        alpha_t = (
            self.alpha
            * jnp.sqrt(1.0 - jnp.power(self.beta2, t))
            / (1.0 - jnp.power(self.beta1, t))
        )

        def upd(w, g, m, v):
            g = g + self.weight_decay * w
            m_new = self.beta1 * m + (1 - self.beta1) * g
            v_new = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
            w_new = w - alpha_t * m_new / (jnp.sqrt(v_new) + self.epsilon)
            return w_new.astype(w.dtype), m_new, v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["m"])
        flat_v = treedef.flatten_up_to(state["v"])
        outs = [
            upd(w, g, m, v)
            for w, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)
        ]
        unf = lambda k: jax.tree_util.tree_unflatten(treedef, [o[k] for o in outs])
        return unf(0), {"step": step, "m": unf(1), "v": unf(2)}
