"""Optimizers: SGD (momentum/nesterov/weight-decay) and Adam.

Re-design of the reference's optimizers (reference: src/runtime/optimizer.cc,
optimizer_kernel.cu:88,196). The reference has two sync modes — PS (gradient
replicas summed on an owner shard) and NCCL (ncclAllReduce then local
update); on TPU gradient synchronization is implicit: the jitted step's
gradients already carry the correct shardings and GSPMD emits psum /
reduce-scatter over ICI where replica groups exist. The update itself is a
pure elementwise function applied shard-wise.

Semantics match the reference kernels exactly:
  SGD   (optimizer_kernel.cu sgd_update): g' = g + wd*w;
        v = momentum*v + g'; w -= lr * (nesterov ? g' + momentum*v : v)
  Adam  (optimizer_kernel.cu adam_update): bias-corrected alpha_t schedule,
        w -= alpha_t * m_hat / (sqrt(v_hat) + eps) with decoupled-style
        wd folded into the gradient (reference applies wd additively).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


class Optimizer:
    def init_state(self, params):
        raise NotImplementedError

    def next_step(self, state):
        """Per-iteration hyper-parameter schedule hook
        (reference: Optimizer::next())."""
        return state

    def update(self, params, grads, state):
        raise NotImplementedError

    # -- sparse (touched-rows-only) updates ---------------------------------
    #
    # The executor's sparse-embedding fast path (executor.py
    # _sparse_embedding_guids) updates only the rows a batch touched.
    # With state (momentum / Adam moments) the semantics are LAZY, the
    # standard sparse-optimizer contract (TF's LazyAdam / sparse momentum):
    # untouched rows' state neither decays nor applies — exactly what
    # keeps the update O(touched rows) instead of O(vocab).

    def supports_sparse(self) -> bool:
        return False

    def split_state(self, state, keys):
        """Remove `keys`' entries from params-mirroring subtrees so
        update() can run on the dense params subset; returns
        (dense_state, {key: {subtree_name: entry}})."""
        keys = set(keys)
        dense = {}
        slots = {k: {} for k in keys}
        for name, v in state.items():
            if isinstance(v, dict) and keys & set(v):
                dense[name] = {g: w for g, w in v.items() if g not in keys}
                for k in keys & set(v):
                    slots[k][name] = v[k]
            else:
                dense[name] = v
        return dense, slots

    def merge_state(self, state, slots):
        out = dict(state)
        for k, slot in slots.items():
            for name, entry in slot.items():
                out[name] = dict(out.get(name, {}))
                out[name][k] = entry
        return out

    def sparse_row_update(self, w, slot, ids, rows, step):
        """Apply the update to rows `ids` of `w` with cotangent `rows`
        ([n, dim] aligned with flattened ids); `slot` is this weight's
        state entry from split_state; `step` the post-increment step."""
        raise NotImplementedError


def _segment_sum_rows(ids, rows):
    """Sum duplicate ids' rows (scatter-add linearity holds for the plain
    gradient but NOT for stateful updates: a momentum/Adam row must see
    the SUMMED gradient once, not one state transition per duplicate).
    Returns (rep_ids, summed_rows, valid) where rep_ids[k] is segment k's
    id for k < num_segments and `valid` masks the tail."""
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sorted_ids = ids[order]
    sorted_rows = rows[order]
    start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]]
    )
    seg = jnp.cumsum(start) - 1  # [n] segment index per row
    summed = jnp.zeros_like(sorted_rows).at[seg].add(sorted_rows)
    rep_ids = jnp.zeros_like(sorted_ids).at[seg].set(sorted_ids)
    valid = jnp.arange(n) < seg[-1] + 1
    return rep_ids, summed, valid


@dataclasses.dataclass(frozen=True)
class SGDOptimizer(Optimizer):
    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0

    def init_state(self, params):
        if self.momentum == 0.0:
            return {"step": jnp.zeros((), jnp.int32)}
        return {
            "step": jnp.zeros((), jnp.int32),
            "velocity": jax.tree_util.tree_map(jnp.zeros_like, params),
        }

    def update(self, params, grads, state):
        wd = self.weight_decay

        if self.momentum == 0.0:
            def upd(w, g):
                g = g + wd * w
                return (w - self.lr * g).astype(w.dtype)

            new_params = jax.tree_util.tree_map(upd, params, grads)
            return new_params, {"step": state["step"] + 1}

        def upd(w, g, v):
            g = g + wd * w
            v_new = self.momentum * v + g
            step = g + self.momentum * v_new if self.nesterov else v_new
            return (w - self.lr * step).astype(w.dtype), v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_v = treedef.flatten_up_to(state["velocity"])
        outs = [upd(w, g, v) for w, g, v in zip(flat_p, flat_g, flat_v)]
        new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
        new_vel = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
        return new_params, {"step": state["step"] + 1, "velocity": new_vel}

    def supports_sparse(self) -> bool:
        return True

    def sparse_row_update(self, w, slot, ids, rows, step):
        """Lazy sparse SGD: weight decay and momentum apply to TOUCHED
        rows only (untouched velocities don't decay — the TF sparse-
        momentum contract; dense SGD would keep moving untouched rows on
        stale velocity, which is exactly the O(vocab) walk this path
        removes)."""
        if self.momentum == 0.0:
            if not self.weight_decay:
                # plain SGD: scatter-add is linear, duplicates just sum
                return w.at[ids].add((-self.lr * rows).astype(w.dtype)), slot
            # wd depends on w[ids]: dedup so each row applies wd once
            rep, summed, valid = _segment_sum_rows(ids, rows)
            g = summed + self.weight_decay * w[rep]
            safe = jnp.where(valid, rep, w.shape[0])
            return (
                w.at[safe].add((-self.lr * g).astype(w.dtype), mode="drop"),
                slot,
            )

        v = slot["velocity"][0]
        rep, summed, valid = _segment_sum_rows(ids, rows)
        g = summed
        if self.weight_decay:
            g = g + self.weight_decay * w[rep]
        v_rows = self.momentum * v[rep] + g
        upd = g + self.momentum * v_rows if self.nesterov else v_rows
        safe = jnp.where(valid, rep, w.shape[0])
        new_v = v.at[safe].set(v_rows.astype(v.dtype), mode="drop")
        new_w = w.at[safe].add((-self.lr * upd).astype(w.dtype), mode="drop")
        return new_w, {"velocity": [new_v]}


@dataclasses.dataclass(frozen=True)
class AdamOptimizer(Optimizer):
    alpha: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    weight_decay: float = 0.0
    epsilon: float = 1e-8

    def init_state(self, params):
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"step": jnp.zeros((), jnp.int32), "m": zeros(), "v": zeros()}

    def update(self, params, grads, state):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        # reference: Optimizer::next() recomputes alpha_t with bias correction
        alpha_t = (
            self.alpha
            * jnp.sqrt(1.0 - jnp.power(self.beta2, t))
            / (1.0 - jnp.power(self.beta1, t))
        )

        def upd(w, g, m, v):
            g = g + self.weight_decay * w
            m_new = self.beta1 * m + (1 - self.beta1) * g
            v_new = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
            w_new = w - alpha_t * m_new / (jnp.sqrt(v_new) + self.epsilon)
            return w_new.astype(w.dtype), m_new, v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["m"])
        flat_v = treedef.flatten_up_to(state["v"])
        outs = [
            upd(w, g, m, v)
            for w, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)
        ]
        unf = lambda k: jax.tree_util.tree_unflatten(treedef, [o[k] for o in outs])
        return unf(0), {"step": step, "m": unf(1), "v": unf(2)}

    def supports_sparse(self) -> bool:
        return True

    def sparse_row_update(self, w, slot, ids, rows, step):
        """Lazy Adam (the standard sparse-Adam contract): moments of
        touched rows update with the summed gradient; untouched rows'
        moments are frozen. Bias correction uses the GLOBAL step, same
        alpha_t as the dense update."""
        t = step.astype(jnp.float32)
        alpha_t = (
            self.alpha
            * jnp.sqrt(1.0 - jnp.power(self.beta2, t))
            / (1.0 - jnp.power(self.beta1, t))
        )
        m, v = slot["m"][0], slot["v"][0]
        rep, summed, valid = _segment_sum_rows(ids, rows)
        g = summed
        if self.weight_decay:
            g = g + self.weight_decay * w[rep]
        m_rows = self.beta1 * m[rep] + (1 - self.beta1) * g
        v_rows = self.beta2 * v[rep] + (1 - self.beta2) * jnp.square(g)
        upd = alpha_t * m_rows / (jnp.sqrt(v_rows) + self.epsilon)
        safe = jnp.where(valid, rep, w.shape[0])
        new_m = m.at[safe].set(m_rows.astype(m.dtype), mode="drop")
        new_v = v.at[safe].set(v_rows.astype(v.dtype), mode="drop")
        new_w = w.at[safe].add((-upd).astype(w.dtype), mode="drop")
        return new_w, {"m": [new_m], "v": [new_v]}
