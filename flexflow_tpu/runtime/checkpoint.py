"""Checkpoint / resume.

The reference has NO model checkpointing subsystem (SURVEY §5: weights can
only be pulled/pushed from Python via Tensor.get_tensor/set_tensor, and only
*strategies* are serializable via --export-strategy). This module is the
"TPU build should do better" item: step-tagged training checkpoints of
params + optimizer state + RNG through orbax when available (multi-host-safe
and async-capable), with a pickle fallback so the subsystem works anywhere.

Layout: `<dir>/step_<N>/` per checkpoint, newest retained up to
`max_to_keep` (oldest deleted on save, like orbax's manager).
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_STEP_PREFIX = "step_"
_INT_KEY = "i~"  # marks dict keys that were ints (guids) before saving


def _stringify(tree):
    """Recursively make dict keys orbax/JSON-safe (int guid -> 'i~<guid>')."""
    if isinstance(tree, dict):
        return {
            (_INT_KEY + str(k)) if isinstance(k, int) else k: _stringify(v)
            for k, v in tree.items()
        }
    if isinstance(tree, (list, tuple)):
        return [_stringify(v) for v in tree]
    return tree


def _unstringify(tree):
    if isinstance(tree, dict):
        return {
            int(k[len(_INT_KEY):]) if isinstance(k, str) and k.startswith(_INT_KEY)
            else k: _unstringify(v)
            for k, v in tree.items()
        }
    if isinstance(tree, (list, tuple)):
        return [_unstringify(v) for v in tree]
    return tree


def _to_host(tree):
    """Device arrays -> numpy (gathers sharded arrays to host)."""
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


class CheckpointManager:
    """Save/restore training state under a directory.

    State is any pytree; FFModel passes {params, opt_state, rng, meta}.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self._orbax = None
        try:
            import orbax.checkpoint as ocp

            self._orbax = ocp
        except Exception:
            pass

    # -- bookkeeping ---------------------------------------------------------

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _prune(self):
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._path(victim), ignore_errors=True)

    # -- save / restore ------------------------------------------------------

    def save(self, step: int, state: Dict[str, Any]):
        """Write one checkpoint; prunes beyond max_to_keep."""
        tree = _stringify(_to_host(state))
        path = self._path(step)
        if os.path.exists(path):
            shutil.rmtree(path)
        if self._orbax is not None:
            ckptr = self._orbax.StandardCheckpointer()
            ckptr.save(os.path.join(path, "state"), tree)
            ckptr.wait_until_finished()
        else:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "state.pkl"), "wb") as f:
                pickle.dump(tree, f)
        self._prune()

    def restore(self, step: Optional[int] = None) -> Tuple[int, Dict[str, Any]]:
        """Load a checkpoint (latest by default); returns (step, state)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        path = self._path(step)
        orbax_path = os.path.join(path, "state")
        pkl_path = os.path.join(path, "state.pkl")
        if self._orbax is not None and os.path.isdir(orbax_path):
            ckptr = self._orbax.StandardCheckpointer()
            tree = ckptr.restore(orbax_path)
        elif os.path.exists(pkl_path):
            with open(pkl_path, "rb") as f:
                tree = pickle.load(f)
        else:
            raise FileNotFoundError(f"checkpoint {path} has no payload")
        return step, _unstringify(tree)
