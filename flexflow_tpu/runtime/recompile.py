"""Dynamic-reconfiguration hook: RecompileState.

TPU rebuild of the reference's recompile subsystem (reference:
src/recompile/recompile_state.cc:1-40, include/flexflow/recompile.h:26-41;
used by the MoE example to rebalance experts mid-training,
examples/cpp/mixture_of_experts/moe.cc:65-99). A `RecompileState` pairs a
trigger predicate with a model-mutating alter function;
`FFModel.recompile_on_condition(state)` checks the trigger each time it is
called from the training loop and, when it fires, mutates the model and
recompiles — preserving weights of every surviving layer whose shape is
unchanged, re-initializing the rest, and resetting optimizer state.

Differences from the reference: the reference alters the live Legion op
graph and re-runs compile() in place; here the builder graph is restored to
its pre-strategy form before `alter_func` runs (strategy annotations and
inserted parallel ops are compile artifacts, not user model structure), so
the alter function sees the same graph shape the user built.

Caveat: a recompile re-applies `model._compile_strategy` as-is. An
EXPLICIT pipeline strategy carries its BlockStructure (block guids) from
the original graph — valid across recompiles whose alter leaves the
trunk intact (graph restore preserves guids), but an alter that adds or
removes trunk blocks must pass a freshly built pipeline strategy to
compile() itself; searched strategies re-derive automatically.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass
class RecompileState:
    """reference: RecompileState {trigger_func, alter_func} (recompile.h)."""

    trigger_func: Callable[["FFModel"], bool]
    alter_func: Callable[["FFModel"], None]
    recompiled: int = 0

    def trigger(self, model) -> bool:
        return bool(self.trigger_func(model))

    def alter(self, model) -> None:
        self.alter_func(model)
        self.recompiled += 1


def recompile_on_condition(model, state: RecompileState) -> bool:
    """Check the trigger; on fire, alter + recompile the model
    (reference: FFModel::recompile_on_condition, model.cc:2416-2420).

    Returns True when a recompile happened.
    """
    if model.executor is None:
        raise RuntimeError("call compile() before recompile_on_condition()")
    if not state.trigger(model):
        return False

    # weights to host, keyed by stable node identity — builder name, or the
    # weight_key a substitution stamped on its replacement node (guids are
    # fresh every compile, so they cannot key weights across recompiles)
    def stable_key(node):
        return node.params.get("weight_key", node.name)

    host = {}
    ambiguous = set()
    # per-guid EXPORT view, not raw storage: a pipelined executor keeps
    # trunk weights stacked under the template guid only — harvesting
    # model.params directly would drop every later block's weights and
    # reinitialize the trunk on recompile
    for guid, ws in model.executor.export_host_params(model.params).items():
        node = model.graph.nodes.get(guid)
        if node is None:
            continue
        key = stable_key(node)
        if key in host:
            ambiguous.add(key)
        host[key] = [np.asarray(w) for w in ws]
    for key in ambiguous:
        host.pop(key, None)

    # restore the user-built graph (pre-strategy), then let alter mutate it.
    # Carry the live guid counter forward: strategy/substitution allocated
    # guids past the pristine copy's counter, and reusing them would alias
    # alter-added nodes with stale refs (logits, host-weight keys).
    live_next_guid = model.graph._next_guid
    model.graph = model._prestrategy_graph.copy()
    model.graph._next_guid = max(model.graph._next_guid, live_next_guid)
    state.alter(model)

    # the builder-graph logits ref (pre-substitution) survives the restore
    # because graph copies preserve guids; a substituted _logits ref would not
    from flexflow_tpu.runtime.model import Tensor

    logits_ref = getattr(model, "_builder_logits_ref", model._logits.ref)
    model.compile(
        optimizer=model.optimizer,
        loss_type=model.loss_type,
        metrics=model.metric_types,
        logits=Tensor(model, logits_ref)
        if logits_ref.guid in model.graph.nodes
        else None,
        devices=model._compile_devices,
        strategy=model._compile_strategy,
    )

    # carry over weights whose stable identity + shape survived the
    # alteration — overlaid on the fresh params' export view and placed
    # in ONE pass (per-weight set_tensor would rebuild a pipelined
    # trunk's pipe-sharded stack per block: O(S^2) device copies)
    new_by_key = {}
    for guid, node in model.graph.nodes.items():
        if not node.weight_shapes:
            continue
        key = stable_key(node)
        new_by_key[key] = None if key in new_by_key else guid
    current = model.executor.export_host_params(model.params)
    changed = False
    for key, ws in host.items():
        guid = new_by_key.get(key)
        if guid is None:
            continue
        node = model.graph.nodes[guid]
        if len(node.weight_shapes) != len(ws):
            continue
        ok = all(
            tuple(arr.shape)
            == tuple(d.size for d in shape.dims if not d.is_replica_dim)
            for arr, shape in zip(ws, node.weight_shapes)
        )
        if ok:
            # cast to the NEW node's declared dtype (an alter may rebuild
            # a same-shape layer at a different precision; set_tensor
            # used to guarantee this cast)
            current[guid] = [
                np.asarray(arr, dtype=shape.dtype.to_jnp())
                for arr, shape in zip(ws, node.weight_shapes)
            ]
            changed = True
    if changed:
        model.params = model.executor.place_params(current)
    # opt_state from compile() stays valid: placement preserves shapes,
    # and a recompile resets momenta by design (the reference re-inits
    # optimizer tasks after recompile too)
    model.recompile_events = getattr(model, "recompile_events", 0) + 1
    return True
