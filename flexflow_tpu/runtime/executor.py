"""PCG → XLA executor.

This is the TPU-native replacement for the reference's entire execution stack
(Legion index launches + FFMapper + CUDA kernels; SURVEY §3.3): the annotated
PCG lowers to ONE pure train-step function, jitted over a
`jax.sharding.Mesh`. Per-op MachineViews/parallel dims become
`with_sharding_constraint`s; GSPMD inserts the collectives the reference's
parallel ops / NCCL allreduce performed explicitly; Legion's begin/end_trace
iteration replay (reference: transformer.cc:192-198) is subsumed by jit
compilation caching.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import LossType, MetricsType, OperatorType
from flexflow_tpu.ops.registry import LowerCtx, infer_shapes, lower_op
from flexflow_tpu.runtime.initializer import default_weight_initializer
from flexflow_tpu.runtime.loss import compute_loss
from flexflow_tpu.runtime.metrics import compute_metrics
from flexflow_tpu.runtime.optimizer import Optimizer


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """The global device mesh the strategy is expressed over.

    axis i of this mesh is what ParallelDim.parallel_idx == i refers to.
    This is the v1 restriction documented in SURVEY §7: every MachineView
    the search picks must be expressible as sub-axes of one global mesh
    (the reference allows arbitrary per-op device sets).
    """

    axis_names: Tuple[str, ...] = ("data",)
    axis_sizes: Tuple[int, ...] = (1,)

    @property
    def num_devices(self) -> int:
        out = 1
        for s in self.axis_sizes:
            out *= s
        return out

    def build_mesh(self, devices=None) -> Mesh:
        devices = jax.devices() if devices is None else list(devices)
        n = self.num_devices
        if len(devices) < n:
            raise ValueError(
                f"mesh needs {n} devices, have {len(devices)}"
            )
        arr = np.array(devices[:n]).reshape(self.axis_sizes)
        return Mesh(arr, self.axis_names)

    @staticmethod
    def data_parallel(num_devices: int) -> "MeshConfig":
        return MeshConfig(("data",), (num_devices,))


def propagate_shapes(graph: PCGGraph):
    """Re-run parallel-shape inference over the whole graph in topo order.

    Called after a strategy annotates source nodes or inserts parallel ops —
    the equivalent of the reference's per-op output-dim solve at PCG
    construction (reference: model.cc:494-647).
    """
    for guid in graph.topo_order():
        node = graph.nodes[guid]
        if not node.inputs:
            (outs, weights) = infer_shapes(node.op_type, [], node.params)
            node.output_shapes = tuple(outs)
            continue
        in_shapes = [graph.shape_of(r) for r in node.inputs]
        outs, weights = infer_shapes(node.op_type, in_shapes, node.params)
        node.output_shapes = tuple(outs)
        node.weight_shapes = tuple(weights)


class Executor:
    """Compiles an annotated PCG into jitted step functions."""

    def __init__(
        self,
        graph: PCGGraph,
        mesh_config: MeshConfig,
        logits_ref: TensorRef,
        label_shape: Optional[ParallelTensorShape] = None,
        loss_type: Optional[LossType] = None,
        metrics: Sequence[MetricsType] = (),
        optimizer: Optional[Optimizer] = None,
        devices=None,
        aux_loss_fns=(),
        logits_from_logits: bool = True,
        mixed_precision: bool = False,
        seq_length: Optional[int] = None,
        sparse_embedding_update: bool = False,
    ):
        self.graph = graph
        self.mesh_config = mesh_config
        self.mesh = mesh_config.build_mesh(devices)
        self.logits_ref = logits_ref
        self.label_shape = label_shape
        self.loss_type = loss_type
        self.metric_types = tuple(metrics)
        self.optimizer = optimizer
        self.aux_loss_fns = tuple(aux_loss_fns)
        self.logits_from_logits = logits_from_logits
        self.mixed_precision = mixed_precision
        self.seq_length = seq_length
        self.sparse_embedding_update = sparse_embedding_update
        self.topo = graph.topo_order()
        self._lowered = {
            g: lower_op(graph.nodes[g].op_type, graph.nodes[g].params)
            for g in self.topo
        }
        # cache ops surface their input to the host memoizer each train
        # step (reference: cache.cc forward stores the batch; here the
        # value rides the metrics pytree out of the jitted step)
        self.cache_guids = [
            g
            for g in self.topo
            if graph.nodes[g].op_type == OperatorType.CACHE
        ]
        self._train_step = None
        self._eval_step = None
        self._fwd = None
        self._grad_fn = None
        # jit-cache telemetry: how many step callables this executor
        # built (each is one XLA compile on first call) and how many
        # times a cached step was dropped (seq-length change, LR
        # rebind) — mirrored into train_jit_* series by FFModel.fit
        self.jit_builds = 0
        self.jit_invalidations = 0

    # -- shardings -----------------------------------------------------------

    def sharding_for(self, shape: ParallelTensorShape) -> NamedSharding:
        spec = shape.partition_spec(
            self.mesh_config.axis_names, self.mesh_config.axis_sizes
        )
        return NamedSharding(self.mesh, spec)

    def _constrain(self, x, shape: ParallelTensorShape):
        if shape.total_degree > 1 and any(
            d.degree > 1 and not d.is_replica_dim for d in shape.dims
        ):
            return jax.lax.with_sharding_constraint(x, self.sharding_for(shape))
        return x

    # -- parameters ----------------------------------------------------------

    def init_params(self, rng, skip_guids=frozenset()) -> Dict[int, List[jnp.ndarray]]:
        """Initialize + shard all weights (reference: initializer tasks at
        Op::init, SURVEY §2.1). skip_guids: nodes a subclass stores
        differently (the pipelined executor's stacked trunk)."""
        params: Dict[int, List[jnp.ndarray]] = {}
        for guid in self.topo:
            node = self.graph.nodes[guid]
            if not node.weight_shapes or guid in skip_guids:
                continue
            ws = []
            inits = node.params.get("initializers")
            for i, wshape in enumerate(node.weight_shapes):
                init = (
                    inits[i]
                    if inits is not None and inits[i] is not None
                    else default_weight_initializer(node.name, i, wshape)
                )
                key = jax.random.fold_in(rng, guid * 131 + i)
                arr = init.create(key, wshape)
                arr = jax.device_put(arr, self.sharding_for(wshape))
                ws.append(arr)
            params[guid] = ws
        return params

    def place_params(
        self, host_params: Dict[int, List[np.ndarray]], skip_guids=frozenset()
    ) -> Dict[int, List[jnp.ndarray]]:
        """Re-shard host weights onto the mesh (checkpoint restore path)."""
        params: Dict[int, List[jnp.ndarray]] = {}
        for guid in self.topo:
            node = self.graph.nodes[guid]
            if not node.weight_shapes or guid in skip_guids:
                continue
            if guid not in host_params:
                raise KeyError(
                    f"checkpoint missing weights for node {guid} ({node.name})"
                )
            ws = []
            for wshape, arr in zip(node.weight_shapes, host_params[guid]):
                expect = tuple(d.size for d in wshape.dims if not d.is_replica_dim)
                if tuple(arr.shape) != expect:
                    raise ValueError(
                        f"checkpoint weight for {node.name} has shape "
                        f"{tuple(arr.shape)}, model expects {expect}"
                    )
                ws.append(jax.device_put(jnp.asarray(arr), self.sharding_for(wshape)))
            params[guid] = ws
        return params

    def reshard_params(self, params, sharding_fn):
        """Re-place a live param tree under NEW shardings — the
        compile-for-serving path, where the serving (data, model) mesh
        differs from the training mesh the weights were initialized on.
        `sharding_fn(node, weight_index, wshape)` returns the target
        `jax.sharding.Sharding` for each weight, or None to leave that
        array untouched. Arrays round-trip through host memory (they
        must be addressable: single-process, or restored host-replicated
        checkpoints on pods) and re-place through
        `multihost.place_array` so multi-process runs materialize only
        locally-owned shards."""
        from flexflow_tpu.runtime import multihost

        out: Dict[int, List[jnp.ndarray]] = {}
        for guid, ws in params.items():
            node = self.graph.nodes[guid]
            new_ws = []
            for i, arr in enumerate(ws):
                sh = sharding_fn(node, i, node.weight_shapes[i])
                if sh is None:
                    new_ws.append(arr)
                else:
                    new_ws.append(multihost.place_array(np.asarray(arr), sh))
            out[guid] = new_ws
        return out

    def export_host_params(self, params):
        """Params in the on-disk checkpoint layout (per-guid). The base
        executor's storage IS that layout (copied, so callers can edit
        without touching live state); the pipelined executor overrides to
        unstack its pipe-sharded trunk."""
        return {g: list(ws) for g, ws in params.items()}

    def export_host_opt_state(self, opt_state):
        """Optimizer state in the on-disk layout: subtrees that mirror
        the params pytree (SGD velocity, Adam m/v) go through the same
        per-guid conversion as the params themselves."""
        out = {}
        for k, v in opt_state.items():
            out[k] = self.export_host_params(v) if isinstance(v, dict) else v
        return out

    def place_opt_state(self, host_state):
        """Restore optimizer state saved by export_host_opt_state: mirror
        subtrees re-place like weights (same shapes/shardings), scalars
        pass through."""
        out = {}
        for k, v in host_state.items():
            out[k] = (
                self.place_params(v)
                if isinstance(v, dict)
                else jnp.asarray(v)
            )
        return out

    def get_host_param(self, params, guid: int, idx: int):
        """One weight, in its logical per-guid shape."""
        return params[guid][idx]

    def set_host_param(self, params, guid: int, idx: int, val):
        """Write one weight in place (val already validated/dtyped)."""
        node = self.graph.nodes[guid]
        params[guid][idx] = jax.device_put(
            val, self.sharding_for(node.weight_shapes[idx])
        )

    # -- forward -------------------------------------------------------------

    def forward_values(
        self,
        params,
        batch,
        rng=None,
        train=True,
        injected=None,
        op_hooks=None,
        constrain=True,
    ):
        """Evaluate the PCG; returns {(guid, out_idx): array}.

        injected: {guid: array} precomputed single-output node values
        (the sparse-embedding fast path differentiates wrt these
        activations instead of the table weights).

        op_hooks: {OperatorType: fn(node, ins, ws, ctx) -> [outs]} —
        per-op-type overrides of the registered lowering. The serving
        engine (flexflow_tpu.serving.engine) re-executes the compiled PCG
        with an attention hook that reads/writes the KV cache; everything
        else runs the normal lowering, so serving reuses this machinery
        instead of growing a second interpreter.

        constrain=False skips the per-tensor sharding constraints — the
        hook path feeds shapes (decode seq length 1) that differ from the
        compiled training shapes, so the recorded PartitionSpecs no
        longer describe the arrays; hooked callers shard their inputs
        explicitly instead."""
        values: Dict[Tuple[int, int], jnp.ndarray] = {}

        def _maybe_constrain(x, shape):
            return self._constrain(x, shape) if constrain else x

        for guid in self.topo:
            node = self.graph.nodes[guid]
            if injected is not None and guid in injected:
                values[(guid, 0)] = _maybe_constrain(
                    injected[guid], node.output_shapes[0]
                )
                continue
            if node.op_type in (OperatorType.INPUT, OperatorType.NOOP) and not node.inputs:
                if node.name not in batch:
                    raise KeyError(f"batch missing input '{node.name}'")
                x = batch[node.name]
                x = _maybe_constrain(x, node.output_shapes[0])
                values[(guid, 0)] = x
                continue
            ins = [values[(r.guid, r.out_idx)] for r in node.inputs]
            ws = params.get(guid, [])
            ctx = LowerCtx(
                train=train,
                rng=None if rng is None else jax.random.fold_in(rng, guid),
                mesh=self.mesh,
                axis_names=self.mesh_config.axis_names,
                in_shapes=[self.graph.shape_of(r) for r in node.inputs],
                bf16_matmul=self.mixed_precision,
                seq_length=self.seq_length,
            )
            hook = op_hooks.get(node.op_type) if op_hooks else None
            if hook is not None:
                outs = hook(node, ins, ws, ctx)
            else:
                outs = self._lowered[guid](ins, ws, ctx)
            for i, out in enumerate(outs):
                out = _maybe_constrain(out, node.output_shapes[i])
                values[(guid, i)] = out
        return values

    def _loss_and_metrics(self, params, batch, rng, train, injected=None):
        values = self.forward_values(params, batch, rng, train, injected)
        logits = values[(self.logits_ref.guid, self.logits_ref.out_idx)]
        labels = batch["label"]
        loss = compute_loss(
            self.loss_type, logits, labels, from_logits=self.logits_from_logits
        )
        for fn in self.aux_loss_fns:
            loss = loss + fn(values, batch)
        mets = compute_metrics(
            self.metric_types, logits, labels, from_logits=self.logits_from_logits
        )
        if train and self.cache_guids:
            mets = dict(mets)
            for guid in self.cache_guids:
                node = self.graph.nodes[guid]
                r = node.inputs[0]
                mets[f"__cache_{node.name}"] = values[(r.guid, r.out_idx)]
        return loss, mets

    # -- compiled entry points ----------------------------------------------

    def _sparse_embedding_guids(self) -> List[int]:
        """EMBEDDING nodes eligible for the sparse-update fast path:
        optimizer supports sparse rows (SGD incl. momentum/wd, Adam — the
        stateful forms have LAZY semantics, Optimizer.sparse_row_update),
        ids read straight from a batch INPUT. Sharded tables (the searched
        model-parallel DLRM embeddings) are eligible: GSPMD partitions the
        gather/scatter, validated vs the dense path on the 8-device mesh
        (tests/test_sparse_embedding.py).

        Why it matters (beyond-reference): autodiff of jnp.take produces a
        DENSE [vocab, dim] cotangent and the optimizer walks the whole
        table every step — for DLRM-class models the tables dominate the
        step. The fast path differentiates wrt the embedding ACTIVATIONS
        and scatter-applies the update to only the touched rows (the
        reference's embedding bwd scatter-adds into a dense grad region
        either way, embedding_kernels.cu:backward)."""
        opt = self.optimizer
        if not self.sparse_embedding_update or opt is None:
            return []
        if not opt.supports_sparse():
            return []
        from flexflow_tpu.core.pcg import trace_embedding_ids_input

        return [
            guid
            for guid in self.topo
            if trace_embedding_ids_input(self.graph, guid) is not None
        ]

    def train_step_fn(self):
        """(params, opt_state, batch, rng) -> (params, opt_state, loss, metrics)"""
        sparse = self._sparse_embedding_guids()
        if not sparse:

            def step(params, opt_state, batch, rng):
                def loss_fn(p):
                    return self._loss_and_metrics(p, batch, rng, train=True)

                (loss, mets), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)
                new_params, new_state = self.optimizer.update(
                    params, grads, opt_state
                )
                return new_params, new_state, loss, mets

            return step

        from flexflow_tpu.core.types import AggrMode
        from flexflow_tpu.ops.registry import LowerCtx

        from flexflow_tpu.core.pcg import trace_embedding_ids_input

        ids_name = {
            g: self.graph.nodes[
                trace_embedding_ids_input(self.graph, g).guid
            ].name
            for g in sparse
        }

        def sparse_step(params, opt_state, batch, rng):
            # forward lookups OUTSIDE the grad closure: the activations
            # become the differentiable leaves, the tables constants
            acts = {}
            for g in sparse:
                node = self.graph.nodes[g]
                ctx = LowerCtx(
                    train=True,
                    rng=None,
                    mesh=self.mesh,
                    axis_names=self.mesh_config.axis_names,
                    in_shapes=[self.graph.shape_of(node.inputs[0])],
                    bf16_matmul=self.mixed_precision,
                    seq_length=self.seq_length,
                )
                acts[g] = self._lowered[g](
                    [batch[ids_name[g]]], [params[g][0]], ctx
                )[0]

            dense = {k: v for k, v in params.items() if k not in sparse}

            def loss_fn(dense_p, acts_in):
                full = dict(dense_p)
                for g in sparse:
                    full[g] = params[g]  # closed-over constant
                return self._loss_and_metrics(
                    full, batch, rng, train=True, injected=acts_in
                )

            (loss, mets), (gd, ga) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(dense, acts)
            # split out the tables' optimizer-state entries so the dense
            # update's pytrees line up, then row-update each table with
            # its slot (Optimizer.sparse_row_update: lazy momentum/Adam)
            dense_state, slots = self.optimizer.split_state(
                opt_state, sparse
            )
            new_params, new_state = self.optimizer.update(
                dense, gd, dense_state
            )
            for g in sparse:
                node = self.graph.nodes[g]
                table = params[g][0]
                ids = batch[ids_name[g]]
                gact = ga[g]
                aggr = node.params.get("aggr", AggrMode.NONE)
                if aggr == AggrMode.SUM:
                    rows = jnp.broadcast_to(
                        gact[..., None, :], ids.shape + gact.shape[-1:]
                    )
                elif aggr == AggrMode.AVG:
                    rows = (
                        jnp.broadcast_to(
                            gact[..., None, :], ids.shape + gact.shape[-1:]
                        )
                        / ids.shape[-1]
                    )
                else:  # NONE: cotangent already one row per id
                    rows = gact
                dim = rows.shape[-1]
                new_table, new_slot = self.optimizer.sparse_row_update(
                    table,
                    slots.get(g),
                    ids.reshape(-1),
                    rows.reshape(-1, dim).astype(table.dtype),
                    new_state["step"],
                )
                new_params[g] = [new_table]
                slots[g] = new_slot
            new_state = self.optimizer.merge_state(new_state, slots)
            return new_params, new_state, loss, mets

        return sparse_step

    def set_seq_length(self, seq_length: Optional[int]):
        """Per-iteration dynamic sequence truncation (reference:
        FFIterationConfig.seq_length, config.h:160-165; threaded into
        BatchMatmul). Changing it invalidates the compiled steps — each
        distinct length is one XLA recompile, like a new Legion trace."""
        if seq_length != self.seq_length:
            self.seq_length = seq_length
            self.jit_invalidations += sum(
                f is not None
                for f in (
                    self._train_step, self._eval_step, self._fwd,
                    self._grad_fn,
                )
            )
            self._train_step = None
            self._eval_step = None
            self._fwd = None
            self._grad_fn = None

    def train_step(self):
        if self._train_step is None:
            self._train_step = jax.jit(self.train_step_fn(), donate_argnums=(0, 1))
            self.jit_builds += 1
        return self._train_step

    def eval_step(self):
        if self._eval_step is None:

            def step(params, batch):
                return self._loss_and_metrics(params, batch, None, train=False)

            self._eval_step = jax.jit(step)
            self.jit_builds += 1
        return self._eval_step

    def grad_fn(self):
        """Loss gradients wrt params: (params, batch) -> grads pytree.
        Dropout/rng-free (train=False), jitted and cached like eval_step."""
        if self._grad_fn is None:

            def grads(params, batch):
                def loss_fn(p):
                    loss, _ = self._loss_and_metrics(
                        p, batch, None, train=False
                    )
                    return loss

                return jax.grad(loss_fn)(params)

            self._grad_fn = jax.jit(grads)
            self.jit_builds += 1
        return self._grad_fn

    def forward_fn(self):
        """Inference forward: (params, batch) -> logits."""
        if self._fwd is None:

            def fwd(params, batch):
                values = self.forward_values(params, batch, None, train=False)
                return values[(self.logits_ref.guid, self.logits_ref.out_idx)]

            self._fwd = jax.jit(fwd)
            self.jit_builds += 1
        return self._fwd

    # -- data placement ------------------------------------------------------

    def shard_batch(self, batch: Dict[str, np.ndarray]):
        """Host→device transfer with each input's searched sharding
        (the TPU analog of the reference's SingleDataLoader index-launched
        shard copies, python/flexflow_dataloader.cc). On multi-host runs
        every process passes the SAME GLOBAL batch and materializes only
        the shards its devices own; one placement loop serves both paths
        (runtime/multihost.place_batch)."""
        from flexflow_tpu.runtime.multihost import place_batch

        return place_batch(self, batch, multi=jax.process_count() > 1)

    def input_shapes(self) -> Dict[str, ParallelTensorShape]:
        out = {}
        for guid in self.topo:
            node = self.graph.nodes[guid]
            if node.op_type == OperatorType.INPUT and not node.inputs:
                out[node.name] = node.output_shapes[0]
        if self.label_shape is not None:
            out["label"] = self.label_shape
        return out
