"""Operator fusion: the FusedOp pass.

Rebuild of the reference's apply_fusion (reference: model.cc:2489-2597 —
greedily folds ops with the same MachineView into one FusedOp so one
Legion task launch runs many kernels; src/ops/fused.cc dispatches the
inner kernels through input/weight/output indirection tables).

On TPU the kernel-level win is already XLA's (everything under one jit
fuses); what remains is PCG-level: fewer nodes to trace/lower/annotate,
and one unit for the search to cost. The pass folds single-consumer
CHAINS of compute ops whose parallel annotations agree; the FUSED node
keeps the sub-op list in params and its lowering applies the inner
lowered functions in order (the indirection-table analog, flattened
weights sliced per sub-op).
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import OperatorType

# ops that may join a fused chain: unary-dataflow compute ops (one input,
# one output). Parallel ops never fuse (they are the view boundaries the
# reference fuses BETWEEN); routing/multi-io ops keep their identity.
_FUSIBLE = {
    OperatorType.LINEAR,
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.ELU,
    OperatorType.GELU,
    OperatorType.IDENTITY,
    OperatorType.EXP,
    OperatorType.SIN,
    OperatorType.COS,
    OperatorType.POW,
    OperatorType.RSQRT,
    OperatorType.SCALAR_MULTIPLY,
    OperatorType.SCALAR_ADD,
    OperatorType.SCALAR_SUB,
    OperatorType.SCALAR_TRUE_DIV,
    OperatorType.DROPOUT,
    OperatorType.SOFTMAX,
    OperatorType.LAYERNORM,
    OperatorType.RESHAPE,
    OperatorType.TRANSPOSE,
    OperatorType.CAST,
    OperatorType.FLAT,
}


def _chain_from(graph: PCGGraph, start: int, claimed: Set[int]) -> list:
    """Longest fusible chain start → … where every link is the sole
    consumer of a single-output predecessor."""
    chain = [start]
    cur = start
    while True:
        node = graph.nodes[cur]
        if node.num_outputs != 1:
            break
        cons = graph.consumers(cur)
        if len(cons) != 1:
            break
        nxt = next(iter(cons))
        nxt_node = graph.nodes[nxt]
        if (
            nxt_node.op_type not in _FUSIBLE
            or nxt in claimed
            or len(nxt_node.inputs) != 1
        ):
            break
        chain.append(nxt)
        cur = nxt
    return chain


def apply_fusion(
    graph: PCGGraph, protected: Optional[Set[int]] = None
) -> Tuple[PCGGraph, Dict[TensorRef, TensorRef]]:
    """Fold fusible chains into FUSED nodes (reference: apply_fusion,
    model.cc:2489). `protected` guids are never absorbed (the logits node —
    callers hold references to it). Returns (new graph, old→new ref map for
    the outputs of fused chains)."""
    protected = protected or set()
    g = graph.copy()
    claimed: Set[int] = set()
    ref_map: Dict[TensorRef, TensorRef] = {}

    for start in list(g.topo_order()):
        if start in claimed or start not in g.nodes:
            continue
        node = g.nodes[start]
        if (
            node.op_type not in _FUSIBLE
            or len(node.inputs) != 1
            or start in protected
        ):
            continue
        # a protected node (logits) may END a chain — its output ref is
        # remapped to the fused node — but never sit inside one (its value
        # must stay addressable)
        chain = []
        for c in _chain_from(g, start, claimed):
            chain.append(c)
            if c in protected:
                break
        if len(chain) < 2:
            continue

        nodes = [g.nodes[c] for c in chain]
        sub_ops = [
            {
                "op_type": n.op_type,
                "params": dict(n.params),
                "num_weights": len(n.weight_shapes),
            }
            for n in nodes
        ]
        inits = []
        have_inits = False
        for n in nodes:
            per = n.params.get("initializers")
            if per is not None:
                have_inits = True
                inits.extend(per)
            else:
                inits.extend([None] * len(n.weight_shapes))
        params = {
            "sub_ops": sub_ops,
            "weight_key": "+".join(
                n.params.get("weight_key", n.name) for n in nodes
            ),
        }
        if have_inits:
            params["initializers"] = inits

        last = nodes[-1]
        fused = g.add_node(
            OperatorType.FUSED,
            "+".join(n.name for n in nodes),
            [nodes[0].inputs[0]],
            params,
            list(last.output_shapes),
            [w for n in nodes for w in n.weight_shapes],
        )
        new_ref = TensorRef(fused.guid, 0)
        old_ref = TensorRef(chain[-1], 0)
        ref_map[old_ref] = new_ref
        for c in list(g.consumers(chain[-1])):
            g.replace_input(c, old_ref, new_ref)
        for c in chain:
            g.remove_node(c)
        claimed.update(chain)
        claimed.add(fused.guid)

    return g, ref_map
