"""Pipeline-parallel executor: GPipe integrated with FFModel.compile().

Round-1 left pipelining as a standalone functional API
(parallel/pipeline.py) disconnected from the PCG executor; this closes
the gap (VERDICT r1 weak #4): a searched or imported dp×pp strategy now
compiles into a normal train_step. The reference only ever DECLARED
pipeline parallelism (OP_PIPELINE enum, ffconst.h:151, no operator), so
this path is beyond-reference capability.

Execution model:
  prologue  — ordinary PCG walk (dp-sharded over the "data" axis);
  trunk     — the repeated blocks found by search.blocks: per-template
              weights of all S blocks are stacked on a leading axis,
              sharded over the "pipe" mesh axis, and streamed through the
              shard_map GPipe schedule (lax.scan + ppermute); each stage
              runs S/pp consecutive blocks via an inner lax.scan;
  epilogue  — ordinary PCG walk on the pipeline output.

v1 restrictions (documented, enforced):
  * block weights are stored per-guid like every other executor weight
    (optimizer/checkpoint machinery unchanged) and stacked inside the
    step; storage is therefore replicated, the pipeline parallelizes
    compute and activation memory, not weight storage;
  * no TP/SP inside a pipelined trunk (the search proposes pp only as a
    (dp, pp) mesh);
  * ops needing the mesh inside the trunk (ring attention) fall back to
    their local lowering — in_shapes passed to the ctx are unannotated.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.ops.registry import LowerCtx
from flexflow_tpu.runtime.executor import Executor
from flexflow_tpu.search.blocks import BlockStructure


@dataclasses.dataclass
class PipelineSpec:
    """How compile() should pipeline the trunk."""

    pp: int
    num_microbatches: int
    structure: BlockStructure

    def validate(self, batch_per_replica: int):
        s = self.structure.num_blocks
        if s % self.pp != 0:
            raise ValueError(
                f"{s} blocks not divisible by pp={self.pp} stages"
            )
        if batch_per_replica % self.num_microbatches != 0:
            raise ValueError(
                f"per-replica batch {batch_per_replica} not divisible by "
                f"num_microbatches={self.num_microbatches}"
            )


class PipelinedExecutor(Executor):
    """Executor whose forward routes the repeated trunk through GPipe."""

    def __init__(self, *args, pipeline_spec: PipelineSpec, **kwargs):
        super().__init__(*args, **kwargs)
        self.pspec = pipeline_spec
        st = pipeline_spec.structure
        for blk in st.blocks:
            for g in blk:
                if self.graph.nodes[g].op_type == OperatorType.CACHE:
                    raise ValueError(
                        "cache ops inside a pipelined trunk are not "
                        "supported (the host memoizer needs the trunk-"
                        "internal activation, which the GPipe schedule "
                        "does not surface); place the cache in the "
                        "prologue/epilogue or use a non-pipeline strategy"
                    )
        self.template = st.blocks[0]
        self.block_pos = {g: i for i, g in enumerate(self.template)}
        self.entry_guid = st.prologue[-1] if st.prologue else None
        self.exit_guid = st.blocks[-1][-1]
        if "pipe" not in self.mesh_config.axis_names:
            raise ValueError("pipelined strategy needs a 'pipe' mesh axis")

    # -- trunk ---------------------------------------------------------------

    def _stacked_trunk_params(self, params):
        """[S, ...]-stacked weights per weight-bearing template position,
        as a tuple-of-tuples pytree (stable structure for shard_map)."""
        blocks = self.pspec.structure.blocks
        stacked = []
        for i, tguid in enumerate(self.template):
            if not self.graph.nodes[tguid].weight_shapes:
                continue
            per_w = []
            for w_idx in range(len(params[tguid])):
                per_w.append(
                    jnp.stack([params[blk[i]][w_idx] for blk in blocks])
                )
            stacked.append(tuple(per_w))
        return tuple(stacked)

    def _block_fn(self, rng, train):
        """One pipeline stage: run S/pp consecutive blocks; stage_params
        leaves carry the per-stage leading axis [blocks_per_stage, ...]."""
        template_nodes = [self.graph.nodes[g] for g in self.template]
        weight_pos = [
            i for i, n in enumerate(template_nodes) if n.weight_shapes
        ]

        def one_block(x, block_ws):
            values: Dict[Tuple[int, int], jnp.ndarray] = {}
            for i, node in enumerate(template_nodes):
                ins = []
                for r in node.inputs:
                    if r.guid in self.block_pos:
                        ins.append(values[(self.block_pos[r.guid], r.out_idx)])
                    else:  # boundary: the previous block's output
                        ins.append(x)
                if i in weight_pos:
                    ws = list(block_ws[weight_pos.index(i)])
                else:
                    ws = []
                ctx = LowerCtx(
                    train=train,
                    # same fold across blocks (v1: block-uniform dropout)
                    rng=None
                    if rng is None
                    else jax.random.fold_in(rng, self.template[i]),
                    bf16_matmul=self.mixed_precision,
                    seq_length=self.seq_length,
                )
                outs = self._lowered[self.template[i]](ins, ws, ctx)
                for o_idx, out in enumerate(outs):
                    values[(i, o_idx)] = out
            return values[(len(template_nodes) - 1, 0)]

        def stage_fn(stage_params, x):
            bps = self.pspec.structure.num_blocks // self.pspec.pp
            if bps == 1:
                local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
                return one_block(x, local)

            def body(carry, ws):
                return one_block(carry, ws), None

            # align the carry dtype with the block's output dtype (bf16
            # activations under mixed precision, mm_out_dtype): blocks are
            # dtype-preserving once the input matches their output
            first_ws = jax.tree_util.tree_map(lambda p: p[0], stage_params)
            out_sd = jax.eval_shape(one_block, x, first_ws)
            if out_sd.dtype != x.dtype:
                x = x.astype(out_sd.dtype)
            out, _ = jax.lax.scan(body, x, stage_params)
            return out

        return stage_fn

    # -- forward -------------------------------------------------------------

    def forward_values(self, params, batch, rng=None, train=True, injected=None):
        if injected:
            raise ValueError(
                "the GPipe executor does not support injected activations "
                "(sparse embedding updates ride the plain executor only)"
            )
        from flexflow_tpu.parallel.pipeline import pipeline_apply

        st = self.pspec.structure
        values: Dict[Tuple[int, int], jnp.ndarray] = {}

        def walk(guids):
            for guid in guids:
                node = self.graph.nodes[guid]
                if (
                    node.op_type in (OperatorType.INPUT, OperatorType.NOOP)
                    and not node.inputs
                ):
                    if node.name not in batch:
                        raise KeyError(f"batch missing input '{node.name}'")
                    x = batch[node.name]
                    x = self._constrain(x, node.output_shapes[0])
                    values[(guid, 0)] = x
                    continue
                ins = [values[(r.guid, r.out_idx)] for r in node.inputs]
                ws = params.get(guid, [])
                ctx = LowerCtx(
                    train=train,
                    rng=None
                    if rng is None
                    else jax.random.fold_in(rng, guid),
                    mesh=self.mesh,
                    axis_names=self.mesh_config.axis_names,
                    in_shapes=[self.graph.shape_of(r) for r in node.inputs],
                    bf16_matmul=self.mixed_precision,
                    seq_length=self.seq_length,
                )
                outs = self._lowered[guid](ins, ws, ctx)
                for i, out in enumerate(outs):
                    out = self._constrain(out, node.output_shapes[i])
                    values[(guid, i)] = out

        walk(st.prologue)
        x = values[(self.entry_guid, 0)]
        data_axis = "data" if "data" in self.mesh_config.axis_names else None
        y = pipeline_apply(
            self.mesh,
            self._block_fn(rng, train),
            self._stacked_trunk_params(params),
            x,
            axis_name="pipe",
            num_microbatches=self.pspec.num_microbatches,
            data_axis=data_axis,
            stage_leading_axis=True,
        )
        # downstream consumers read the LAST block's output
        values[(self.exit_guid, 0)] = y
        walk(st.epilogue)
        return values
