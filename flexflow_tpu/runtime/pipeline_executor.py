"""Pipeline-parallel executor: GPipe integrated with FFModel.compile().

Round-1 left pipelining as a standalone functional API
(parallel/pipeline.py) disconnected from the PCG executor; this closes
the gap (VERDICT r1 weak #4): a searched or imported dp×pp strategy now
compiles into a normal train_step. The reference only ever DECLARED
pipeline parallelism (OP_PIPELINE enum, ffconst.h:151, no operator), so
this path is beyond-reference capability.

Execution model:
  prologue  — ordinary PCG walk (dp-sharded over the "data" axis);
  trunk     — the repeated blocks found by search.blocks: per-template
              weights of all S blocks are stacked on a leading axis,
              sharded over the "pipe" mesh axis, and streamed through the
              shard_map GPipe schedule (lax.scan + ppermute); each stage
              runs S/pp consecutive blocks via an inner lax.scan;
  epilogue  — ordinary PCG walk on the pipeline output.

Weight storage (round 3): trunk weights are stored STACKED per template
position — one [S, ...] array per weight, leading (block) axis sharded
over the "pipe" mesh axis — so each stage holds only its S/pp blocks'
weights plus optimizer state. This is the thing pipeline parallelism
exists for at scale: a trunk too big for one chip fits sharded.
Checkpoints stay per-block on disk (export_host_params unstacks,
place_params re-stacks), so pipeline checkpoints restore into DP
strategies and vice versa.

Remaining v1 restrictions (documented, enforced):
  * no TP/SP inside a pipelined trunk (the search proposes pp only as a
    (dp, pp) mesh);
  * ops needing the mesh inside the trunk (ring attention) fall back to
    their local lowering — in_shapes passed to the ctx are unannotated.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.ops.registry import LowerCtx
from flexflow_tpu.runtime.executor import Executor
from flexflow_tpu.search.blocks import BlockStructure


@dataclasses.dataclass
class PipelineSpec:
    """How compile() should pipeline the trunk.

    schedule: "gpipe" stores every block's internal activations for the
    backward; "1f1b" rematerializes each block body, so stored residuals
    shrink to the stage-boundary activations. In this SPMD lax.scan
    formulation the reverse-mode schedule already interleaves one
    microbatch backward per step (the autodiff of the scan), matching
    1F1B's steady state and bubble count — what distinguishes 1F1B is
    its BOUNDED per-stage activation memory, which the remat delivers
    (see test_pipeline_sharded.py::test_1f1b_bounds_activation_memory).
    """

    pp: int
    num_microbatches: int
    structure: BlockStructure
    schedule: str = "gpipe"

    def validate(self, batch_per_replica: int):
        s = self.structure.num_blocks
        if s % self.pp != 0:
            raise ValueError(
                f"{s} blocks not divisible by pp={self.pp} stages"
            )
        if batch_per_replica % self.num_microbatches != 0:
            raise ValueError(
                f"per-replica batch {batch_per_replica} not divisible by "
                f"num_microbatches={self.num_microbatches}"
            )
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be gpipe|1f1b, got {self.schedule!r}"
            )


class PipelinedExecutor(Executor):
    """Executor whose forward routes the repeated trunk through GPipe."""

    def __init__(self, *args, pipeline_spec: PipelineSpec, **kwargs):
        super().__init__(*args, **kwargs)
        self.pspec = pipeline_spec
        st = pipeline_spec.structure
        for blk in st.blocks:
            for g in blk:
                node = self.graph.nodes[g]
                if node.op_type == OperatorType.CACHE:
                    raise ValueError(
                        "cache ops inside a pipelined trunk are not "
                        "supported (the host memoizer needs the trunk-"
                        "internal activation, which the GPipe schedule "
                        "does not surface); place the cache in the "
                        "prologue/epilogue or use a non-pipeline strategy"
                    )
                if (
                    node.op_type
                    in (OperatorType.AGGREGATE, OperatorType.AGGREGATE_SPEC)
                    and float(node.params.get("lambda_bal", 0.0)) > 0.0
                ):
                    raise ValueError(
                        "the MoE load-balance loss (lambda_bal > 0) inside "
                        "a pipelined trunk is not supported: the balance "
                        "term reads trunk-internal gate activations the "
                        "GPipe schedule does not surface. Use "
                        "lambda_bal=0.0 under pipeline strategies, or a "
                        "non-pipeline strategy"
                    )
        self.template = st.blocks[0]
        self.block_pos = {g: i for i, g in enumerate(self.template)}
        self.entry_guid = st.prologue[-1] if st.prologue else None
        self.exit_guid = st.blocks[-1][-1]
        if "pipe" not in self.mesh_config.axis_names:
            raise ValueError("pipelined strategy needs a 'pipe' mesh axis")
        # trunk guids beyond block 0 have no entry in params: their
        # weights live in block 0's (template) stacked arrays
        self._later_block_guids = {
            g for blk in st.blocks[1:] for g in blk
        }
        # guid -> (block index, template position) for per-weight access
        self._block_index = {
            g: (bi, i)
            for bi, blk in enumerate(st.blocks)
            for i, g in enumerate(blk)
        }

    # -- trunk weight storage ------------------------------------------------
    #
    # Canonical storage: params[template_guid][w] is the [S, ...] STACK of
    # all blocks' weights for that template position, sharded over "pipe"
    # on the leading axis — each stage's devices hold only their S/pp
    # blocks (+ the optimizer state that follows the pytree). The search's
    # memory model divides the trunk weight term by pp accordingly
    # (search/auto.py:_pipeline_candidate).

    def _stack_sharding(self, wshape):
        from jax.sharding import NamedSharding, PartitionSpec

        ndim = sum(1 for d in wshape.dims if not d.is_replica_dim)
        return NamedSharding(
            self.mesh, PartitionSpec("pipe", *([None] * ndim))
        )

    def init_params(self, rng):
        """Non-trunk weights as usual; trunk weights initialized INSIDE a
        jitted builder with pipe-sharded out_shardings, so no chip (or
        host transfer) ever materializes the full replicated stack. Each
        block's slice uses the same fold_in key the plain executor would
        give that block — a pipelined model starts bit-identical to its
        DP lowering (the loss-parity tests rely on this)."""
        from flexflow_tpu.runtime.initializer import (
            default_weight_initializer,
        )

        params = super().init_params(
            rng, skip_guids=self._later_block_guids | set(self.template)
        )
        blocks = self.pspec.structure.blocks
        for i, tguid in enumerate(self.template):
            node = self.graph.nodes[tguid]
            if not node.weight_shapes:
                continue
            ws = []
            inits = node.params.get("initializers")
            for w_idx, wshape in enumerate(node.weight_shapes):
                init = (
                    inits[w_idx]
                    if inits is not None and inits[w_idx] is not None
                    else default_weight_initializer(node.name, w_idx, wshape)
                )

                def build(init=init, w_idx=w_idx, i=i):
                    return jnp.stack(
                        [
                            init.create(
                                jax.random.fold_in(
                                    rng, blk[i] * 131 + w_idx
                                ),
                                wshape,
                            )
                            for blk in blocks
                        ]
                    )

                ws.append(
                    jax.jit(
                        build, out_shardings=self._stack_sharding(wshape)
                    )()
                )
            params[tguid] = ws
        return params

    def place_params(self, host_params):
        """Checkpoint-restore path. Accepts per-block host weights (the
        on-disk format, shared with every other executor) or an
        already-stacked [S, ...] layout, and re-shards over "pipe"."""
        blocks = self.pspec.structure.blocks
        S = len(blocks)
        params = super().place_params(
            host_params,
            skip_guids=self._later_block_guids | set(self.template),
        )
        for i, tguid in enumerate(self.template):
            node = self.graph.nodes[tguid]
            if not node.weight_shapes:
                continue
            ws = []
            for w_idx, wshape in enumerate(node.weight_shapes):
                expect = tuple(
                    d.size for d in wshape.dims if not d.is_replica_dim
                )
                if tguid in host_params and tuple(
                    np.shape(host_params[tguid][w_idx])
                ) == (S,) + expect:
                    stacked = jnp.asarray(host_params[tguid][w_idx])
                else:
                    per_block = []
                    for blk in blocks:
                        if blk[i] not in host_params:
                            raise KeyError(
                                f"checkpoint missing weights for block "
                                f"node {blk[i]} ({node.name})"
                            )
                        arr = host_params[blk[i]][w_idx]
                        if tuple(np.shape(arr)) != expect:
                            raise ValueError(
                                f"checkpoint weight for {node.name} has "
                                f"shape {tuple(np.shape(arr))}, model "
                                f"expects {expect}"
                            )
                        per_block.append(jnp.asarray(arr))
                    stacked = jnp.stack(per_block)
                ws.append(
                    jax.device_put(stacked, self._stack_sharding(wshape))
                )
            params[tguid] = ws
        return params

    def export_host_params(self, params):
        """Unstack trunk storage into the per-block on-disk layout, so a
        pipeline checkpoint restores into ANY strategy (and vice versa)."""
        tmpl = set(self.template)
        out = {
            g: list(ws) for g, ws in params.items() if g not in tmpl
        }
        blocks = self.pspec.structure.blocks
        for i, tguid in enumerate(self.template):
            if not self.graph.nodes[tguid].weight_shapes:
                continue
            for bi, blk in enumerate(blocks):
                out[blk[i]] = [w[bi] for w in params[tguid]]
        return out

    def get_host_param(self, params, guid: int, idx: int):
        """One weight in its logical shape — trunk weights read their
        single [bi] slice of the stack, not the whole export view."""
        loc = self._block_index.get(guid)
        if loc is None:
            return params[guid][idx]
        bi, i = loc
        return params[self.template[i]][idx][bi]

    def set_host_param(self, params, guid: int, idx: int, val):
        loc = self._block_index.get(guid)
        if loc is None:
            return super().set_host_param(params, guid, idx, val)
        bi, i = loc
        tguid = self.template[i]
        # .at[].set keeps the pipe sharding of the stacked storage
        params[tguid][idx] = params[tguid][idx].at[bi].set(val)

    def _stacked_trunk_params(self, params):
        """The shard_map-ready tuple-of-tuples view of the trunk storage
        (already stacked and pipe-sharded — a direct read)."""
        stacked = []
        for tguid in self.template:
            if not self.graph.nodes[tguid].weight_shapes:
                continue
            stacked.append(tuple(params[tguid]))
        return tuple(stacked)

    def _block_fn(self, rng, train):
        """One pipeline stage: run S/pp consecutive blocks; stage_params
        leaves carry the per-stage leading axis [blocks_per_stage, ...]."""
        template_nodes = [self.graph.nodes[g] for g in self.template]
        weight_pos = [
            i for i, n in enumerate(template_nodes) if n.weight_shapes
        ]

        def one_block(x, block_ws):
            values: Dict[Tuple[int, int], jnp.ndarray] = {}
            for i, node in enumerate(template_nodes):
                ins = []
                for r in node.inputs:
                    if r.guid in self.block_pos:
                        ins.append(values[(self.block_pos[r.guid], r.out_idx)])
                    else:  # boundary: the previous block's output
                        ins.append(x)
                if i in weight_pos:
                    ws = list(block_ws[weight_pos.index(i)])
                else:
                    ws = []
                ctx = LowerCtx(
                    train=train,
                    # same fold across blocks (v1: block-uniform dropout)
                    rng=None
                    if rng is None
                    else jax.random.fold_in(rng, self.template[i]),
                    bf16_matmul=self.mixed_precision,
                    seq_length=self.seq_length,
                )
                outs = self._lowered[self.template[i]](ins, ws, ctx)
                for o_idx, out in enumerate(outs):
                    values[(i, o_idx)] = out
            return values[(len(template_nodes) - 1, 0)]

        if self.pspec.schedule == "1f1b":
            # the reverse scan already interleaves microbatch backwards
            # 1F1B-style (PipelineSpec docstring); remat'ing each block
            # body delivers 1F1B's bounded activation memory — stored
            # residuals shrink to stage-boundary activations
            one_block = jax.checkpoint(one_block)

        def stage_fn(stage_params, x):
            bps = self.pspec.structure.num_blocks // self.pspec.pp
            if bps == 1:
                local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
                return one_block(x, local)

            def body(carry, ws):
                return one_block(carry, ws), None

            # align the carry dtype with the block's output dtype (bf16
            # activations under mixed precision, mm_out_dtype): blocks are
            # dtype-preserving once the input matches their output
            first_ws = jax.tree_util.tree_map(lambda p: p[0], stage_params)
            out_sd = jax.eval_shape(one_block, x, first_ws)
            if out_sd.dtype != x.dtype:
                x = x.astype(out_sd.dtype)
            out, _ = jax.lax.scan(body, x, stage_params)
            return out

        return stage_fn

    # -- forward -------------------------------------------------------------

    def forward_values(self, params, batch, rng=None, train=True, injected=None):
        if injected:
            raise ValueError(
                "the GPipe executor does not support injected activations "
                "(sparse embedding updates ride the plain executor only)"
            )
        from flexflow_tpu.parallel.pipeline import pipeline_apply

        st = self.pspec.structure
        values: Dict[Tuple[int, int], jnp.ndarray] = {}

        def walk(guids):
            for guid in guids:
                node = self.graph.nodes[guid]
                if (
                    node.op_type in (OperatorType.INPUT, OperatorType.NOOP)
                    and not node.inputs
                ):
                    if node.name not in batch:
                        raise KeyError(f"batch missing input '{node.name}'")
                    x = batch[node.name]
                    x = self._constrain(x, node.output_shapes[0])
                    values[(guid, 0)] = x
                    continue
                ins = [values[(r.guid, r.out_idx)] for r in node.inputs]
                ws = params.get(guid, [])
                ctx = LowerCtx(
                    train=train,
                    rng=None
                    if rng is None
                    else jax.random.fold_in(rng, guid),
                    mesh=self.mesh,
                    axis_names=self.mesh_config.axis_names,
                    in_shapes=[self.graph.shape_of(r) for r in node.inputs],
                    bf16_matmul=self.mixed_precision,
                    seq_length=self.seq_length,
                )
                outs = self._lowered[guid](ins, ws, ctx)
                for i, out in enumerate(outs):
                    out = self._constrain(out, node.output_shapes[i])
                    values[(guid, i)] = out

        walk(st.prologue)
        x = values[(self.entry_guid, 0)]
        data_axis = "data" if "data" in self.mesh_config.axis_names else None
        y = pipeline_apply(
            self.mesh,
            self._block_fn(rng, train),
            self._stacked_trunk_params(params),
            x,
            axis_name="pipe",
            num_microbatches=self.pspec.num_microbatches,
            data_axis=data_axis,
            stage_leading_axis=True,
        )
        # downstream consumers read the LAST block's output
        values[(self.exit_guid, 0)] = y
        walk(st.epilogue)
        return values
