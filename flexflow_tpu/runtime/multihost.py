"""Multi-host (multi-process) runtime support.

TPU-native replacement for the reference's multi-node stack — GASNet/MPI
process bootstrap (reference: CMake FF_USE_GASNET + conduits,
.github/workflows/multinode-test.yml:29-74 runs `mpirun -np 2`) and the
per-MachineView NCCL communicator setup (reference: model.cc:3115-3153).
Here the collectives are XLA's, compiled from sharding annotations; what
remains host-side is (a) process bootstrap, (b) building ONE global mesh
whose outer axis rides the slow DCN links and whose inner axes ride ICI,
and (c) assembling global device arrays from per-host local batches.

On Cloud TPU pods `initialize()` needs no arguments — JAX discovers the
coordinator from the TPU metadata. On CPU/GPU clusters pass
coordinator_address/num_processes/process_id (the mpirun analog).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
):
    """Bootstrap the JAX distributed runtime (idempotent; single-process
    callers may skip it entirely). The analog of Legion's
    `Runtime::start` under GASNet + the NCCL id exchange.

    MUST run before any other JAX call: even `jax.process_count()`
    initializes the local backend and poisons the distributed bootstrap,
    so idempotency is checked against the distributed client itself."""
    import jax

    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        try:
            from jax._src import distributed as _dist

            state = _dist.global_state
        except ImportError:
            state = None  # private module moved: fall back to catching
            # the public initialize()'s already-initialized error below
    if state is not None and getattr(state, "client", None) is not None:
        return  # already initialized
    if (
        coordinator_address is None
        and num_processes is None
        and process_id is None
    ):
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            # single-process run without a cluster environment: fine
            return
    else:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:
            # keep the documented idempotency when the client state was
            # not inspectable (future-JAX fallback above)
            if "already" not in str(e).lower():
                raise


def is_primary() -> bool:
    """True on the process that should print/save (reference: Legion
    control replication prints once from node 0)."""
    import jax

    return jax.process_index() == 0


def global_mesh(
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    devices=None,
):
    """Build a Mesh over ALL processes' devices with DCN-friendly
    placement: `mesh_utils.create_device_mesh` keeps ICI neighbors
    adjacent on the inner axes, so the OUTERMOST axis (by convention the
    "data" axis — gradient all-reduce tolerates DCN latency, activations
    do not) is the one crossing hosts. The scaling-mesh recipe the
    reference approximates with its node-major MachineViews
    (machine_view.h:62-96). `devices` restricts the mesh to an explicit
    device list (serving meshes may use a subset of the machine);
    `create_device_mesh` requires len(devices) == prod(axis_sizes)."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    grid = mesh_utils.create_device_mesh(
        tuple(axis_sizes), devices=devices
    )
    return Mesh(grid, tuple(axis_names))


def place_array(value, sharding=None, multi: Optional[bool] = None):
    """Place ONE host array onto devices — the single-array core of
    `place_batch`, exposed so the serving placement layer
    (serving/distributed.py) routes KV pools and scheduler-assembled
    global batches through the same path. multi defaults to "is this a
    multi-process run"; when true every process passes the SAME global
    value and only the locally-owned shards materialize."""
    import jax

    if multi is None:
        multi = jax.process_count() > 1
    if sharding is None:
        return jax.device_put(value)
    if multi:
        g = np.asarray(value)
        return jax.make_array_from_callback(
            g.shape, sharding, lambda idx: g[idx]
        )
    return jax.device_put(value, sharding)


def place_batch(
    executor, batch: Dict[str, np.ndarray], multi: bool
) -> Dict[str, "np.ndarray"]:
    """THE batch-placement loop (single source of truth for both the
    single- and multi-host paths — Executor.shard_batch delegates here).

    multi=False: plain device_put with each input's searched sharding.
    multi=True: every process passes the SAME GLOBAL batch (fit()'s
    loader yields config.batch_size global rows identically everywhere)
    and `jax.make_array_from_callback` materializes only the shards this
    process's devices own — the analog of the reference's
    SingleDataLoader index-launch shard copies
    (python/flexflow_dataloader.cc: every node sees the whole dataset in
    zero-copy memory; each GPU's task copies out just its slice)."""
    import jax

    shapes = executor.input_shapes()
    out = {}
    for name, arr in batch.items():
        if name in shapes:
            sharding = executor.sharding_for(shapes[name])
            out[name] = place_array(arr, sharding, multi=multi)
        else:
            out[name] = place_array(arr)
    return out


def shard_host_batch(
    executor, batch: Dict[str, np.ndarray]
) -> Dict[str, "np.ndarray"]:
    """Multi-host batch assembly from the global batch (works unchanged at
    process_count == 1; tests/multihost_helpers exercises it at 2)."""
    return place_batch(executor, batch, multi=True)
