"""Loss functions (reference: src/loss_functions/loss_functions.cc:39-100 —
Loss::backward seeds output grads with scale 1/batch for CE, 2/volume for
MSE; here losses are scalar functions and jax.grad produces those seeds)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.core.types import LossType


def compute_loss(loss_type: LossType, logits, labels, from_logits=True):
    """`from_logits=False` when the graph's final op is already a Softmax —
    the reference's CE losses always consume softmax probabilities
    (loss_functions.cc seeds grads assuming softmax outputs)."""
    if loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
        if from_logits:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-12, 1.0))
        if labels.ndim == logits.ndim and labels.shape[-1] == 1:
            # the reference's label tensor is [batch, 1] (sparse class
            # index per sample, loss_functions.cc) — native-python
            # scripts reshape labels that way; squeeze to index form
            labels = labels[..., 0]
        ll = jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[..., None], axis=-1
        )[..., 0]
        return -jnp.mean(ll)
    if loss_type == LossType.CATEGORICAL_CROSSENTROPY:
        if from_logits:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-12, 1.0))
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))
    if loss_type == LossType.MEAN_SQUARED_ERROR_AVG_REDUCE:
        return jnp.mean(jnp.square(logits.astype(jnp.float32) - labels))
    if loss_type == LossType.MEAN_SQUARED_ERROR_SUM_REDUCE:
        # reference scales grads by 2/volume but sums over the class dim
        return jnp.mean(
            jnp.sum(jnp.square(logits.astype(jnp.float32) - labels), axis=-1)
        )
    if loss_type == LossType.IDENTITY:
        return jnp.mean(logits.astype(jnp.float32))
    raise ValueError(f"unknown loss {loss_type}")
