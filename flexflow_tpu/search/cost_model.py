"""Per-op and per-collective cost model for the strategy search.

Replaces the reference's Simulator op-cost measurement + analytic xfer cost
(reference: src/runtime/simulator.cc:532-756, src/runtime/model.cu:38-74 —
real-kernel timing cached by (OperatorParameters, MachineView)) with a
TPU-appropriate split:

  * **analytic roofline** per op: time = max(FLOPs / MXU peak, bytes / HBM
    bandwidth). This is the default so the search runs without hardware
    (reference's --search-num-workers override, model.cc:3673-3680).
  * **measured mode**: jit the op's lowered function on its *shard* shapes on
    the real chip, time it, and cache by (params_hash, shard shapes) — the
    direct analog of inner_measure_operator_cost. Under XLA an isolated-op
    time over-counts what fusion removes, so measurement is reserved for the
    big MXU ops where it is accurate (matmul/conv/attention).
  * **collective costs** from ring formulas over ICI: all-reduce moves
    2·(n-1)/n · bytes per link, all-gather/reduce-scatter (n-1)/n · bytes,
    all-to-all (n-1)/n · bytes with full bisection.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.types import DataType, OperatorType
from flexflow_tpu.ops.registry import op_flops


@dataclasses.dataclass
class OpCost:
    """reference: CostMetrics {forward_time, backward_time, sync_time,
    memory} (simulator.h:54-79). Times in seconds, memory in bytes/chip."""

    forward_time: float = 0.0
    backward_time: float = 0.0
    sync_time: float = 0.0
    memory: int = 0

    @property
    def total(self) -> float:
        return self.forward_time + self.backward_time + self.sync_time


# ops whose FLOPs dominate (MXU ops); everything else is bandwidth-bound
_MXU_OPS = {
    OperatorType.LINEAR,
    OperatorType.CONV2D,
    OperatorType.BATCHMATMUL,
    OperatorType.MULTIHEAD_ATTENTION,
}

# collective latency floor per hop (ICI); dominates small messages
_ICI_LATENCY_S = 1e-6
_DEFAULT_EFFICIENCY = 0.6  # achievable fraction of peak (MXU and ICI alike)


class CostModel:
    def __init__(
        self,
        spec: MachineSpec,
        measure: bool = False,
        efficiency: float = _DEFAULT_EFFICIENCY,
        machine_model=None,
        mixed_precision: bool = False,
    ):
        """machine_model: an optional search.machine_model.MachineModel
        (Enhanced / Networked); when given, collectives are costed as ring
        steps over its actual comm paths instead of the flat ICI formulas
        (reference: the simulator routes messages over
        MachineModel::get_comm_path, simulator.cc:810+).

        mixed_precision: cost f32 tensors at 2 bytes/element — under the
        executor's bf16 mode (FFConfig.allow_mixed_precision) activations
        and matmul operands live in bfloat16, so every HBM and wire term
        halves. Master weights stay f32 for the optimizer, but the grad
        all-reduce also rides bf16; the per-element approximation is
        uniform by design and documented here."""
        self.spec = spec
        self.measure = measure
        self.efficiency = efficiency
        self.machine_model = machine_model
        self.mixed_precision = mixed_precision
        self._measured: Dict[Tuple[int, Tuple], float] = {}

    def elem_bytes(self, shape: ParallelTensorShape) -> int:
        """Bytes per element the executor will actually move for this
        tensor (the reference hardcodes sizeof(float) throughout its
        simulator; dtype-awareness is a deliberate improvement).

        Only f32 downcasts: the executor's mm_operands casts f32 matmul
        operands to bf16 and nothing else (ops/registry.py)."""
        if self.mixed_precision and shape.dtype == DataType.FLOAT:
            return 2
        return shape.dtype.size_bytes

    def piece_bytes(self, shape: ParallelTensorShape) -> float:
        """Per-shard bytes under this cost model's precision rules."""
        return shape.piece_volume() * self.elem_bytes(shape)

    # -- collectives --------------------------------------------------------

    def _ici_time(self, bytes_on_wire: float, hops: int = 1) -> float:
        bw = self.spec.ici_gbps * 1e9 * self.efficiency
        return bytes_on_wire / bw + hops * _ICI_LATENCY_S

    def _ring_step(
        self,
        bytes_per_step: float,
        group_size: int,
        chips: Optional[Sequence[int]] = None,
    ) -> float:
        """One ring step over the machine model's paths: ring neighbors
        exchange concurrently, so the step takes as long as the slowest
        pair. `chips` are the group's actual device ids — a cross-node or
        strided group rings over its real (possibly DCN) paths; without it
        the group is assumed contiguous at the machine origin."""
        mm = self.machine_model
        if chips is None:
            chips = range(min(group_size, mm.num_chips()))
        ids = [c % mm.num_chips() for c in chips]
        worst = 0.0
        for i, src in enumerate(ids):
            dst = ids[(i + 1) % len(ids)]
            worst = max(worst, mm.transfer_time(src, dst, bytes_per_step))
        return worst

    def all_reduce(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return 2 * (group_size - 1) * self._ring_step(
                bytes_per_chip / group_size, group_size, chips
            )
        wire = 2.0 * (group_size - 1) / group_size * bytes_per_chip
        return self._ici_time(wire, hops=2 * (group_size - 1))

    def all_gather(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return (group_size - 1) * self._ring_step(
                bytes_per_chip, group_size, chips
            )
        wire = (group_size - 1) / group_size * bytes_per_chip * group_size
        return self._ici_time(wire, hops=group_size - 1)

    def reduce_scatter(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return (group_size - 1) * self._ring_step(
                bytes_per_chip / group_size, group_size, chips
            )
        wire = (group_size - 1) / group_size * bytes_per_chip
        return self._ici_time(wire, hops=group_size - 1)

    def all_to_all(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return (group_size - 1) * self._ring_step(
                bytes_per_chip / group_size, group_size, chips
            )
        wire = (group_size - 1) / group_size * bytes_per_chip
        return self._ici_time(wire, hops=group_size - 1)

    # -- compute ------------------------------------------------------------

    def _roofline(self, flops: float, bytes_moved: float) -> float:
        t_flops = flops / (self.spec.peak_tflops * 1e12 * self.efficiency)
        t_mem = bytes_moved / (self.spec.hbm_gbps * 1e9 * self.efficiency)
        return max(t_flops, t_mem)

    def op_cost(self, node, input_shapes: Sequence[ParallelTensorShape]) -> OpCost:
        """Cost of one op on ONE chip's shard, fwd + bwd.

        Shard sizing: global FLOPs / total_degree of the output — per-dim
        degrees multiply into how many ways the work is split. Parallel ops
        are costed by the simulator (they are communication, not compute).
        """
        out = node.output_shapes[0] if node.output_shapes else None
        if out is None:
            return OpCost()
        degree = max(1, out.total_degree)
        flops = op_flops(node.op_type, input_shapes, node.params) / degree

        _pb = self.piece_bytes
        bytes_moved = sum(_pb(s) for s in input_shapes)
        bytes_moved += sum(_pb(s) for s in node.output_shapes)
        bytes_moved += sum(_pb(s) for s in node.weight_shapes)
        mem = sum(_pb(s) for s in node.output_shapes)
        mem += sum(_pb(s) for s in node.weight_shapes)

        if self.measure and node.op_type in _MXU_OPS:
            fwd = self._measure_op(node, input_shapes)
            if fwd is not None:
                # bwd of a matmul-family op = two matmuls of the same size
                return OpCost(fwd, 2.0 * fwd, 0.0, mem)

        fwd = self._roofline(flops, bytes_moved)
        # backward: dX and dW each cost about one forward for MXU ops;
        # elementwise backward re-reads the same bytes.
        bwd = 2.0 * fwd if node.op_type in _MXU_OPS else fwd
        return OpCost(fwd, bwd, 0.0, mem)

    # -- measured mode ------------------------------------------------------

    def _measure_op(self, node, input_shapes) -> Optional[float]:
        """Time the real lowered kernel on shard shapes (reference:
        inner_measure_operator_cost, model.cu:38-74). Cached like the
        reference's hash_to_op_cost (simulator.cc:532-572)."""
        key = (
            node.params_hash(),
            tuple(s.piece_sizes for s in input_shapes),
        )
        if key in self._measured:
            return self._measured[key]
        try:
            import time

            import jax
            import jax.numpy as jnp

            from flexflow_tpu.ops.registry import LowerCtx, lower_op

            fn = lower_op(node.op_type, node.params)
            ins = [
                jnp.zeros(
                    tuple(
                        d.piece_size
                        for d in s.dims
                        if not d.is_replica_dim
                    ),
                    s.dtype.to_jnp(),
                )
                for s in input_shapes
            ]
            ws = [
                jnp.zeros(
                    tuple(
                        d.piece_size
                        for d in s.dims
                        if not d.is_replica_dim
                    ),
                    s.dtype.to_jnp(),
                )
                for s in node.weight_shapes
            ]
            ctx = LowerCtx(train=False, rng=None)
            jitted = jax.jit(lambda i, w: fn(i, w, ctx))
            outs = jitted(ins, ws)  # compile + warmup
            jax.block_until_ready(outs)
            reps = 5
            t0 = time.perf_counter()
            for _ in range(reps):
                outs = jitted(ins, ws)
            jax.block_until_ready(outs)
            t = (time.perf_counter() - t0) / reps
            self._measured[key] = t
            return t
        except Exception:
            self._measured[key] = None
            return None
