"""Per-op and per-collective cost model for the strategy search.

Replaces the reference's Simulator op-cost measurement + analytic xfer cost
(reference: src/runtime/simulator.cc:532-756, src/runtime/model.cu:38-74 —
real-kernel timing cached by (OperatorParameters, MachineView)) with a
TPU-appropriate split:

  * **analytic roofline** per op: time = max(FLOPs / MXU peak, bytes / HBM
    bandwidth). This is the default so the search runs without hardware
    (reference's --search-num-workers override, model.cc:3673-3680).
  * **measured mode**: jit the op's lowered function on its *shard* shapes on
    the real chip, time it, and cache by (params_hash, shard shapes) — the
    direct analog of inner_measure_operator_cost. Under XLA an isolated-op
    time over-counts what fusion removes, so measurement is reserved for the
    big MXU ops where it is accurate (matmul/conv/attention).
  * **collective costs** from ring formulas over ICI: all-reduce moves
    2·(n-1)/n · bytes per link, all-gather/reduce-scatter (n-1)/n · bytes,
    all-to-all (n-1)/n · bytes with full bisection.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.types import DataType, OperatorType
from flexflow_tpu.ops.registry import op_flops


@dataclasses.dataclass
class OpCost:
    """reference: CostMetrics {forward_time, backward_time, sync_time,
    memory} (simulator.h:54-79). Times in seconds, memory in bytes/chip."""

    forward_time: float = 0.0
    backward_time: float = 0.0
    sync_time: float = 0.0
    memory: int = 0

    @property
    def total(self) -> float:
        return self.forward_time + self.backward_time + self.sync_time


# ops whose FLOPs dominate (MXU ops); everything else is bandwidth-bound
_MXU_OPS = {
    OperatorType.LINEAR,
    OperatorType.CONV2D,
    OperatorType.BATCHMATMUL,
    OperatorType.MULTIHEAD_ATTENTION,
}

# ops worth timing for real in measured mode: the MXU set plus Embedding,
# whose backward materializes a dense table-sized gradient the roofline
# badly mis-prices (the dominant cost of DLRM-class models)
_MEASURED_OPS = _MXU_OPS | {OperatorType.EMBEDDING}

# op family for the cross-family residual correction (calibrate.py
# --fit-family): isolated-chain measurement over/under-counts what XLA
# fuses across op boundaries by a FAMILY-shaped factor (conv towers fuse
# BN/relu/residual epilogues the chain measurement only partially sees;
# dense stacks fuse less). The fitted full-step residual per family is
# persisted in the calibration table and divided out of measured costs.
_OP_FAMILY = {
    OperatorType.CONV2D: "conv",
    OperatorType.LINEAR: "dense",
    OperatorType.BATCHMATMUL: "dense",
    # attention gets its OWN family (round 5): the isolated chunked-scan
    # measurement over-reads the in-context cost ~1.5x while plain dense
    # stacks read ~0.9x — opposite biases one shared "dense" scale was
    # splitting the difference on (scripts/probe_attn_pricing.py:
    # attn-only 1.50, mlp-only 0.92, full flagship 1.43)
    OperatorType.MULTIHEAD_ATTENTION: "attention",
    OperatorType.EMBEDDING: "embed",
}


def op_family(op_type) -> Optional[str]:
    """Family key for the measured-mode residual correction; None for ops
    that never take the measured path."""
    return _OP_FAMILY.get(op_type)


def shard_batch(input_shapes) -> Optional[int]:
    """Leading (sample) dim piece size of the first input — the batch key
    for the per-regime family correction (family_scale_for)."""
    for s in input_shapes:
        for d in s.dims:
            if not d.is_replica_dim:
                return int(d.piece_size)
    return None


def update_calibration_doc(
    path: str, updates: dict, chip: str = "", replace=(), ops_keep=None
):
    """Read-merge-atomic-write of the calibration table — the ONE home for
    this logic (CostModel flushes, calibrate.py --tune-flash/--fit-family
    all write through here). Tolerates a missing/corrupt file; a doc
    measured on a DIFFERENT chip is dropped, not relabeled (its ops/
    family_scale/flash_blocks would silently mis-tune the new chip).
    Dict-valued updates shallow-merge into the existing value so partial
    writers (a one-family --fit-family run) don't wipe sibling entries;
    keys named in `replace` are OVERWRITTEN instead. `ops_keep` (a set of
    keys) filters the 'ops' table INSIDE the lock after merging —
    calibrate.py --prune drops stale shape-signature formats and
    abandoned configs without racing a concurrent writer's fresh keys (a
    snapshot taken outside the lock could overwrite them).

    Concurrent writers (two searches sharing one table) are serialized by
    an fcntl lock on `path + ".lock"` around the read-merge-write, so
    neither loses the other's freshly measured keys. Same-host only — the
    lock does not protect a table on NFS."""
    import json
    import os

    lock = None
    try:
        import fcntl

        lock = open(path + ".lock", "w")
        fcntl.flock(lock, fcntl.LOCK_EX)
    except (ImportError, OSError):
        if lock is not None:
            lock.close()  # opened but unlockable (some network mounts)
        lock = None  # non-POSIX: single-writer assumption applies

    try:
        return _update_calibration_doc_locked(
            path, updates, chip, replace, ops_keep
        )
    finally:
        if lock is not None:
            lock.close()


def _update_calibration_doc_locked(path, updates, chip, replace, ops_keep):
    import json
    import os

    doc = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
    if chip and doc.get("chip") not in (None, chip):
        # dropping a foreign-chip table is correct (its entries would
        # mis-tune this chip) but must not be silent or unrecoverable:
        # chip time went into it
        import warnings

        bak = f"{path}.foreign-{doc.get('chip')}.bak"
        try:
            with open(bak, "w") as f:
                json.dump(doc, f, indent=1)
        except OSError:
            bak = "<backup failed>"
        warnings.warn(
            f"calibration table {path} was measured on chip "
            f"{doc.get('chip')!r} but this write targets {chip!r}; "
            f"dropping the foreign table (saved to {bak})",
            stacklevel=2,
        )
        doc = {}
    doc["version"] = 1
    if chip:
        doc["chip"] = chip
    for key, val in updates.items():
        if (
            key not in replace
            and isinstance(val, dict)
            and isinstance(doc.get(key), dict)
        ):
            doc[key].update(val)
        else:
            doc[key] = val
    if ops_keep is not None:
        doc["ops"] = {
            k: v for k, v in doc.get("ops", {}).items() if k in ops_keep
        }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return doc

# collective latency floor per hop (ICI); dominates small messages
_ICI_LATENCY_S = 1e-6
_DEFAULT_EFFICIENCY = 0.6  # achievable fraction of peak (MXU and ICI alike)


class CostModel:
    def __init__(
        self,
        spec: MachineSpec,
        measure: bool = False,
        efficiency: float = _DEFAULT_EFFICIENCY,
        machine_model=None,
        mixed_precision: bool = False,
        calibration_file: str = "",
        sparse_embedding: bool = True,
        family_correction: bool = True,
    ):
        """machine_model: an optional search.machine_model.MachineModel
        (Enhanced / Networked); when given, collectives are costed as ring
        steps over its actual comm paths instead of the flat ICI formulas
        (reference: the simulator routes messages over
        MachineModel::get_comm_path, simulator.cc:810+).

        mixed_precision: cost f32 tensors at 2 bytes/element — under the
        executor's bf16 mode (FFConfig.allow_mixed_precision) activations
        and matmul operands live in bfloat16, so every HBM and wire term
        halves. Master weights stay f32 for the optimizer, but the grad
        all-reduce also rides bf16; the per-element approximation is
        uniform by design and documented here."""
        self.spec = spec
        self.measure = measure
        self.efficiency = efficiency
        self.machine_model = machine_model
        self.mixed_precision = mixed_precision
        # mirror of FFConfig.sparse_embedding_update: eligible tables'
        # optimizer traffic is touched-rows-sized (sparse_update_cost)
        self.sparse_embedding = sparse_embedding
        # measured-mode cache: stable string key -> (fwd_s, bwd_s) | None
        # (reference: hash_to_operator_cost, simulator.cc:532-572). When
        # calibration_file is set the table persists across processes, so
        # one real-chip calibration run serves every later search.
        self._measured: Dict[str, Optional[Tuple[float, float]]] = {}
        self.calibration_file = calibration_file
        # per-family full-step residual (predicted/measured) fitted by
        # `calibrate.py --fit-family`; measured op costs are divided by
        # their family's factor. family_correction=False is the fitting
        # path itself (residuals must be computed without the correction).
        self.family_correction = family_correction
        self._family_scale: Dict[str, float] = {}
        # measured seconds attributed per family across this instance's
        # lifetime (fwd+bwd, post-correction) — calibrate.py --fit-family
        # reads it to split a predicted step into family vs remainder
        self.family_time: Dict[str, float] = {}
        # per-program measurement overhead (dispatch_floor); None = not
        # yet resolved this instance. _loaded_floor holds the table's
        # persisted value; dispatch_floor() min-combines it with a fresh
        # probe (contention only inflates the probe)
        self._dispatch_floor: Optional[float] = None
        self._loaded_floor: Optional[float] = None
        if calibration_file:
            self._load_calibration()

    def elem_bytes(self, shape: ParallelTensorShape) -> int:
        """Bytes per element the executor will actually move for this
        tensor (the reference hardcodes sizeof(float) throughout its
        simulator; dtype-awareness is a deliberate improvement).

        Only f32 downcasts: the executor's mm_operands casts f32 matmul
        operands to bf16 and nothing else (ops/registry.py)."""
        if self.mixed_precision and shape.dtype == DataType.FLOAT:
            return 2
        return shape.dtype.size_bytes

    def piece_bytes(self, shape: ParallelTensorShape) -> float:
        """Per-shard bytes under this cost model's precision rules."""
        return shape.piece_volume() * self.elem_bytes(shape)

    # -- collectives --------------------------------------------------------

    def _ici_time(self, bytes_on_wire: float, hops: int = 1) -> float:
        bw = self.spec.ici_gbps * 1e9 * self.efficiency
        return bytes_on_wire / bw + hops * _ICI_LATENCY_S

    def _ring_step(
        self,
        bytes_per_step: float,
        group_size: int,
        chips: Optional[Sequence[int]] = None,
    ) -> float:
        """One ring step over the machine model's paths: ring neighbors
        exchange concurrently, so the step takes as long as the slowest
        pair. `chips` are the group's actual device ids — a cross-node or
        strided group rings over its real (possibly DCN) paths; without it
        the group is assumed contiguous at the machine origin."""
        mm = self.machine_model
        if chips is None:
            chips = range(min(group_size, mm.num_chips()))
        ids = [c % mm.num_chips() for c in chips]
        worst = 0.0
        for i, src in enumerate(ids):
            dst = ids[(i + 1) % len(ids)]
            worst = max(worst, mm.transfer_time(src, dst, bytes_per_step))
        return worst

    def all_reduce(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return 2 * (group_size - 1) * self._ring_step(
                bytes_per_chip / group_size, group_size, chips
            )
        wire = 2.0 * (group_size - 1) / group_size * bytes_per_chip
        return self._ici_time(wire, hops=2 * (group_size - 1))

    def all_gather(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return (group_size - 1) * self._ring_step(
                bytes_per_chip, group_size, chips
            )
        wire = (group_size - 1) / group_size * bytes_per_chip * group_size
        return self._ici_time(wire, hops=group_size - 1)

    def reduce_scatter(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return (group_size - 1) * self._ring_step(
                bytes_per_chip / group_size, group_size, chips
            )
        wire = (group_size - 1) / group_size * bytes_per_chip
        return self._ici_time(wire, hops=group_size - 1)

    def all_to_all(
        self, bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        if group_size <= 1 or bytes_per_chip <= 0:
            return 0.0
        if self.machine_model is not None:
            return (group_size - 1) * self._ring_step(
                bytes_per_chip / group_size, group_size, chips
            )
        wire = (group_size - 1) / group_size * bytes_per_chip
        return self._ici_time(wire, hops=group_size - 1)

    def swap_cost(self, bytes_moved: float) -> float:
        """Seconds to stage `bytes_moved` across the chip<->host link —
        the price of KV swap-to-host (serving/scheduler.py weighs it
        against estimate_recompute_step when picking swap vs recompute
        for a preemption victim). Uses the machine model's PCIe comm
        device when one is attached (NetworkedMachineModel models the
        host link explicitly); otherwise the same defaults that device
        is built from: 32 GB/s x efficiency, 2 us setup latency."""
        if bytes_moved <= 0:
            return 0.0
        pcie = getattr(self.machine_model, "_pcie", None)
        if pcie is not None:
            return pcie.latency_s + bytes_moved / pcie.bandwidth_Bps
        bw = 32.0 * 1e9 * self.efficiency
        return 2e-6 + bytes_moved / bw

    # -- compute ------------------------------------------------------------

    def _roofline(
        self, flops: float, bytes_moved: float, efficiency: float = None
    ) -> float:
        """efficiency=1.0 gives the TRUE lower bound (the measurement
        clamp); the default self.efficiency gives the cost ESTIMATE."""
        eff = self.efficiency if efficiency is None else efficiency
        t_flops = flops / (self.spec.peak_tflops * 1e12 * eff)
        t_mem = bytes_moved / (self.spec.hbm_gbps * 1e9 * eff)
        return max(t_flops, t_mem)

    def op_cost(
        self,
        node,
        input_shapes: Sequence[ParallelTensorShape],
        skip_measure: bool = False,
    ) -> OpCost:
        """Cost of one op on ONE chip's shard, fwd + bwd.

        Shard sizing: global FLOPs / total_degree of the output — per-dim
        degrees multiply into how many ways the work is split. Parallel ops
        are costed by the simulator (they are communication, not compute).
        skip_measure: don't run the isolated kernel measurement (a caller
        already has a chain measurement for this node and only needs the
        analytic memory/roofline terms)."""
        out = node.output_shapes[0] if node.output_shapes else None
        if out is None:
            return OpCost()
        degree = max(1, out.total_degree)
        flops = op_flops(node.op_type, input_shapes, node.params) / degree

        _pb = self.piece_bytes
        bytes_moved = sum(_pb(s) for s in input_shapes)
        bytes_moved += sum(_pb(s) for s in node.output_shapes)
        bytes_moved += sum(_pb(s) for s in node.weight_shapes)
        mem = sum(_pb(s) for s in node.output_shapes)
        mem += sum(_pb(s) for s in node.weight_shapes)

        if self.measure and not skip_measure and node.op_type in _MEASURED_OPS:
            times = self.measured_times_floor_adjusted(
                node.op_type, node.params, input_shapes, node.weight_shapes
            )
            if times is not None:
                times = self.corrected_times(
                    node.op_type, times, batch=shard_batch(input_shapes)
                )
                return OpCost(times[0], times[1], 0.0, mem)

        fwd = self._roofline(flops, bytes_moved)
        # backward: dX and dW each cost about one forward for MXU ops;
        # elementwise backward re-reads the same bytes.
        bwd = 2.0 * fwd if node.op_type in _MXU_OPS else fwd

        # conv halo exchange under a partitioned spatial dim (attribute
        # parallelism): each shard trades (kernel-1)/2 boundary rows with
        # both neighbors per step — GSPMD's windowed-op halo — fwd and
        # again (twice) for the input/weight gradients. Without this term
        # spatial splits cost exactly compute/degree and the search is
        # biased toward them.
        if node.op_type == OperatorType.CONV2D and input_shapes:
            x0 = input_shapes[0]
            kh = int(node.params.get("kernel_h", 1))
            for i, d in enumerate(x0.dims):
                if d.is_replica_dim or d.degree <= 1 or i == 0:
                    continue
                if i == 1 and x0.ndim == 4 and kh > 1:  # H dim sharded
                    w_piece = x0.dims[2].piece_size
                    c = x0.dims[3].size
                    b_piece = x0.dims[0].piece_size
                    halo_bytes = (
                        2 * (kh // 2) * b_piece * w_piece * c
                        * self.elem_bytes(x0)
                    )
                    fwd += self._ici_time(halo_bytes)
                    bwd += 2.0 * self._ici_time(halo_bytes)

        # attention under a partitioned sequence dim — two lowerings
        # (ops/attention.py seq_parallel):
        #   ring    — each device passes its K/V block around the ring
        #             (sp-1) times fwd, ~2x bwd, each hop OVERLAPPED with
        #             the previous block's score compute -> max(comp, comm)
        #   ulysses — all-to-all the seq sharding onto heads before the
        #             core and back after: 3 input pieces + 1 output piece
        #             reshard fwd (mirrored bwd), BLOCKING -> added.
        # The runtime's seq_parallel="auto" takes the ring path, so "auto"
        # costs as ring; the search flips a node to "ulysses" only where
        # this model says the blocking reshard beats the ring (short seq /
        # many heads — comm-dominated) and heads divide sp.
        if (
            node.op_type == OperatorType.MULTIHEAD_ATTENTION
            and input_shapes
        ):
            x0 = input_shapes[0]
            seq_deg = 1
            for i, d in enumerate(x0.dims):
                if not d.is_replica_dim and i == 1 and d.degree > 1:
                    seq_deg = d.degree
            if seq_deg > 1:
                mode = node.params.get("seq_parallel", "auto")
                if mode == "ulysses":
                    x_piece = x0.piece_volume() * self.elem_bytes(x0)
                    a2a_fwd = self.all_to_all(4.0 * x_piece, seq_deg)
                    fwd += a2a_fwd
                    bwd += a2a_fwd  # cotangents reshard the same way
                else:
                    kv_piece = 2 * x0.piece_volume() * self.elem_bytes(x0)
                    ring = (seq_deg - 1) * self._ici_time(kv_piece)
                    fwd = max(fwd, ring)
                    bwd = max(bwd, 2.0 * ring)
        return OpCost(fwd, bwd, 0.0, mem)

    # -- decode (serving) cost family ---------------------------------------
    #
    # The autoregressive decode step the serving engine runs
    # (flexflow_tpu.serving.engine) lives in a different cost regime than
    # the training step this model was built for: one query token turns
    # every matmul into a [b, 1, k]·[k, n] GEMV whose time is the WEIGHT
    # bytes over HBM (re-read every generated token), and attention reads
    # the slot's KV cache instead of materializing an [s, s] score block.
    # That inversion is why the serving search (search/auto.py
    # optimize_serving) picks a different strategy than training: TP over
    # heads/columns divides the dominant weight-read term, while DP at
    # batch 1 leaves chips idle. This family prices exactly that regime;
    # it is analytic-only (the measured path times training shapes).

    def decode_op_cost(
        self,
        node,
        batch: int,
        kv_len: int,
        tp: int = 1,
        page_size: int = 0,
        kernel: str = "dense",
        kv_dtype: str = "fp32",
    ) -> OpCost:
        """Forward cost of ONE decode step of this op on one chip.

        batch: in-flight sequences this chip serves (the dp shard of the
        scheduler's active set); kv_len: cache positions attended (the
        working sequence length); tp: model-axis degree sharding this
        op's weights (heads for attention, columns for linear, rows for
        embedding) — callers pass 1 for ops the candidate leaves
        replicated. memory is the per-chip steady-state footprint the
        feasibility check needs: weights/tp plus this op's KV-cache
        block (serving holds no optimizer state).

        page_size > 0 prices the block-paged cache layout
        (serving/kv_cache.PagedKVCache): a sequence at kv_len positions
        holds (and the decode step streams) ceil(kv_len / page_size)
        whole pages, so the KV term rounds UP to page granularity — the
        per-sequence rounding waste paging pays for its pool-level
        packing win, which optimize_serving's max-in-flight estimate
        prices on the other side.

        kernel selects the attention core's memory-bound term: "pallas"
        prices the flash-decode kernel path (ops/pallas/decode_kernel
        .py) — the cache bytes are read ONCE at page granularity,
        straight from the pool through the block table; "dense" (the
        fallback) prices the jnp gather path on the paged layout, which
        materializes a contiguous per-step cache view first — one extra
        write plus one extra read of the gathered bytes on top of the
        pool read, so the dense paged KV term is 3x the kernel's. On
        the contiguous layout the two paths move the same bytes and the
        term is unchanged.

        kv_dtype "int8" (paged-only, serving/kv_cache quantized pools)
        prices cache rows at 1 byte each plus one fp32 dequant-scale
        read per touched (page, head) for K and V — the bandwidth win
        that pairs with the 4x capacity win estimate_max_in_flight
        prices on the footprint side."""
        tp = max(1, tp)
        elem = lambda s: self.elem_bytes(s)  # noqa: E731
        weight_bytes = sum(
            s.volume() * elem(s) for s in node.weight_shapes
        ) / tp
        out = node.output_shapes[0] if node.output_shapes else None
        feat = out.logical_sizes[-1] if out is not None else 1
        out_elem = elem(out) if out is not None else 4
        act_bytes = float(batch) * feat * out_elem / tp
        flops = 2.0 * batch * sum(s.volume() for s in node.weight_shapes) / tp
        mem = weight_bytes
        bytes_moved = weight_bytes + act_bytes
        if node.op_type == OperatorType.MULTIHEAD_ATTENTION:
            heads = int(node.params["num_heads"]) // tp
            head_dim = int(node.params["embed_dim"]) // max(
                1, int(node.params["num_heads"])
            )
            kv_rows = kv_len
            if page_size > 0:
                kv_rows = -(-kv_len // page_size) * page_size
            cache_elem = 1 if kv_dtype == "int8" else out_elem
            cache_bytes = 2.0 * batch * kv_rows * heads * head_dim * cache_elem
            if kv_dtype == "int8" and page_size > 0:
                # one fp32 scale per touched (page, head), K and V each
                cache_bytes += (
                    2.0 * batch * (kv_rows // page_size) * heads * 4.0
                )
            mem += cache_bytes
            if page_size > 0 and kernel != "pallas":
                # dense fallback on the paged layout: gather the pages
                # into a contiguous view (write), then attend over it
                # (read) — on top of the pool read itself
                cache_bytes *= 3.0
            bytes_moved += cache_bytes
            flops += 4.0 * batch * kv_len * heads * head_dim
        elif node.op_type == OperatorType.EMBEDDING:
            # one row gather per sequence — the table is read sparsely,
            # not streamed; weights count toward memory, not bandwidth
            dim = int(node.params["out_dim"])
            bytes_moved = float(batch) * dim * out_elem + act_bytes
            flops = 0.0
        return OpCost(
            forward_time=self._roofline(flops, bytes_moved),
            backward_time=0.0,
            memory=int(mem),
        )

    def verify_op_cost(
        self,
        node,
        batch: int,
        kv_len: int,
        k: int,
        tp: int = 1,
        page_size: int = 0,
        kernel: str = "dense",
        kv_dtype: str = "fp32",
        tree_nodes: int = 0,
    ) -> OpCost:
        """Forward cost of ONE speculative-decoding verify step of this
        op on one chip: k+1 token positions per sequence (the last
        emitted token plus k drafted tokens) scored in a single call
        (serving/engine.GenerationEngine.verify). tree_nodes > 0 prices
        the token-TREE verify instead (engine.verify_tree): the row
        width becomes 1 + tree_nodes whatever k says — a tree node
        costs exactly what a chain draft position costs (one scored
        row, one fresh cache row); only the acceptance model differs,
        and that lives in optimize_spec_tree.

        The term structure is WHY speculative decoding wins: the weight
        bytes — the decode regime's dominant cost — stream ONCE for all
        k+1 positions, exactly as in decode_op_cost; only the
        activation traffic and FLOPs scale with k+1, and attention
        additionally reads the k fresh cache rows the drafts occupy
        (page-rounded like decode when page_size > 0). So
        verify(k) << (k+1) * decode, and the gap times the measured
        acceptance rate is the speedup optimize_spec_k prices.

        kernel as in decode_op_cost: "pallas" prices the flash-verify
        kernel's single page-granular cache read; "dense" adds the
        paged gather's extra write + read of the contiguous view.
        kv_dtype "int8" as in decode_op_cost: 1-byte cache rows plus
        per-(page, head) fp32 scale reads."""
        tp = max(1, tp)
        w = (1 + int(tree_nodes)) if tree_nodes > 0 else (int(k) + 1)
        elem = lambda s: self.elem_bytes(s)  # noqa: E731
        weight_bytes = sum(
            s.volume() * elem(s) for s in node.weight_shapes
        ) / tp
        out = node.output_shapes[0] if node.output_shapes else None
        feat = out.logical_sizes[-1] if out is not None else 1
        out_elem = elem(out) if out is not None else 4
        act_bytes = float(batch) * w * feat * out_elem / tp
        flops = (
            2.0 * batch * w * sum(s.volume() for s in node.weight_shapes) / tp
        )
        mem = weight_bytes
        bytes_moved = weight_bytes + act_bytes
        if node.op_type == OperatorType.MULTIHEAD_ATTENTION:
            heads = int(node.params["num_heads"]) // tp
            head_dim = int(node.params["embed_dim"]) // max(
                1, int(node.params["num_heads"])
            )
            kv_rows = kv_len + w
            if page_size > 0:
                kv_rows = -(-kv_rows // page_size) * page_size
            cache_elem = 1 if kv_dtype == "int8" else out_elem
            cache_bytes = 2.0 * batch * kv_rows * heads * head_dim * cache_elem
            if kv_dtype == "int8" and page_size > 0:
                cache_bytes += (
                    2.0 * batch * (kv_rows // page_size) * heads * 4.0
                )
            mem += cache_bytes
            if page_size > 0 and kernel != "pallas":
                # dense gather tax, as in decode_op_cost
                cache_bytes *= 3.0
            bytes_moved += cache_bytes
            flops += 4.0 * batch * w * (kv_len + w) * heads * head_dim
        elif node.op_type == OperatorType.EMBEDDING:
            # w row gathers per sequence, like decode's one
            dim = int(node.params["out_dim"])
            bytes_moved = float(batch) * w * dim * out_elem + act_bytes
            flops = 0.0
        return OpCost(
            forward_time=self._roofline(flops, bytes_moved),
            backward_time=0.0,
            memory=int(mem),
        )

    def adapter_delta_cost(
        self,
        batch: int,
        hidden: int,
        rank: int,
        positions: int = 1,
        tp: int = 1,
    ) -> OpCost:
        """Forward cost of the per-step multi-LoRA epilogue on one chip
        (serving/tenancy/adapters.apply_adapter_qkv/_out): per in-flight
        sequence, gather that slot's rank-`rank` A/B pages from the
        paged adapter pool and add (x @ A) @ B to each of the four
        attention projections (q, k, v, out).

        The regime matches decode: the gathers are the cost. Each of
        the 4 projections reads rank rows of A ([hidden, rank]) and B
        ([rank, hidden]) per sequence — adapter pages are slot-gathered,
        not broadcast, so the bytes scale with batch, unlike the base
        weight stream decode_op_cost prices once. FLOPs are the two
        skinny matmuls, 2·b·w·hidden·rank each side. At typical ranks
        (8-64) this is single-digit percent of the base weight read,
        which is why the identity path (`adapter_id = -1`) costs only
        the predicated add it skips. memory is the live pool pages'
        steady-state footprint share attributable to these sequences."""
        tp = max(1, tp)
        b = max(0, int(batch))
        w = max(1, int(positions))
        h = max(1, int(hidden)) // tp
        r = max(1, int(rank))
        # A + B rows for q, k, v, out — gathered per sequence, fp32
        gather_bytes = 4.0 * b * (h * r + r * h) * 4.0
        act_bytes = 4.0 * b * w * (r + h) * 4.0
        flops = 4.0 * (2.0 * b * w * h * r + 2.0 * b * w * r * h)
        return OpCost(
            forward_time=self._roofline(flops, gather_bytes + act_bytes),
            backward_time=0.0,
            memory=int(gather_bytes),
        )

    def prefill_op_cost(
        self,
        node,
        batch: int,
        seq_len: int,
        tp: int = 1,
        page_size: int = 0,
        kernel: str = "dense",
        kv_dtype: str = "fp32",
    ) -> OpCost:
        """Forward cost of ONE prefill of `seq_len` token positions of
        this op on one chip, against an empty cache — a verify step with
        kv_len 0 and w = seq_len positions, which is exactly the shape
        the engine runs (verify IS a prefill-shaped call). Exists so
        preemption-by-recompute can be priced: a preempted sequence's
        recovery bill is one prefill over prompt + generated-so-far
        (search/auto.estimate_recompute_step), the number that decides
        whether optimistic admission's extra in-flight sequences pay for
        the recompute they occasionally trigger."""
        return self.verify_op_cost(
            node,
            batch,
            kv_len=0,
            k=max(0, int(seq_len) - 1),
            tp=tp,
            page_size=page_size,
            kernel=kernel,
            kv_dtype=kv_dtype,
        )

    def prefill_chunk_cost(
        self,
        node,
        batch: int,
        cursor: int,
        chunk: int,
        tp: int = 1,
        page_size: int = 0,
        kernel: str = "dense",
        kv_dtype: str = "fp32",
    ) -> OpCost:
        """Forward cost of ONE chunked-prefill step of this op on one
        chip: `chunk` prompt positions appended at cache cursor
        `cursor` (tokens already prefilled — the staircase mask's
        query_offset). This is exactly the verify shape the engine
        routes chunks through (a chunk is a wide verify with nothing to
        accept), so it prices as verify_op_cost with kv_len = cursor
        and w = chunk positions. The whole-prompt prefill is the
        cursor=0, chunk=seq_len special case (prefill_op_cost), and the
        SUM over a prompt's chunks exceeds the monolithic cost by one
        weight-stream per extra chunk — the price auto.
        optimize_token_budget weighs against the head-of-line latency
        the chunking removes."""
        return self.verify_op_cost(
            node,
            batch,
            kv_len=int(cursor),
            k=max(0, int(chunk) - 1),
            tp=tp,
            page_size=page_size,
            kernel=kernel,
            kv_dtype=kv_dtype,
        )

    # -- measured mode ------------------------------------------------------
    #
    # The direct analog of the reference's inner_measure_operator_cost
    # (model.cu:38-74, cached per (OperatorParameters, MachineView) in
    # simulator.cc:532-572), adapted to the two TPU realities the analytic
    # path cannot capture:
    #   * XLA fusion and MXU tiling make real op time diverge from the
    #     roofline in shape-dependent ways;
    #   * on the axon-tunneled platform block_until_ready does NOT
    #     synchronize, so timing uses the readback-differencing methodology
    #     BASELINE.md established for bench.py: two chained runs of n1 and
    #     n2 dispatches, each ended by ONE scalar readback, differenced so
    #     the tunnel RTT and dispatch constants cancel. Each dispatch runs
    #     _MEASURE_CHAIN scan-chained kernel applications whose inputs are
    #     data-dependent on the previous iteration (a 1e-30-scaled scalar
    #     perturbation), so XLA cannot hoist the body out of the loop.

    _MEASURE_CHAIN = 8
    # differencing needs the timed work to dominate the tunnel's per-call
    # jitter (~ms): grow the dispatch count until the differenced window
    # exceeds _MEASURE_MIN_DIFF_S (or the cap is hit for very large ops)
    _MEASURE_MIN_DIFF_S = 0.25
    _MEASURE_MAX_CALLS = 512

    def _shard_key(
        self, op_type, params: dict, in_shapes, weight_shapes
    ) -> str:
        """Stable (across processes — no salted hash()) cache key."""
        p = ",".join(f"{k}={params[k]!r}" for k in sorted(params))
        def fmt(shapes):
            return ";".join(
                "x".join(
                    str(d.piece_size)
                    for d in s.dims
                    if not d.is_replica_dim
                )
                + ":" + s.dtype.value
                for s in shapes
            )
        return (
            f"{op_type.name}|{p}|in={fmt(in_shapes)}|w={fmt(weight_shapes)}"
            f"|mp{int(self.mixed_precision)}"
        )

    def measure_shard(
        self, op_type, params: dict, in_shapes, weight_shapes
    ) -> Optional[Tuple[float, float]]:
        """(forward_s, backward_s) of the real jitted kernel on SHARD
        shapes (each shape's piece_sizes are what one chip sees). Returns
        None when the op cannot be measured (lowering error, odd params);
        callers fall back to the roofline. One-op case of
        measure_shard_chain (shared cache/persistence policy)."""
        return self.measure_shard_chain(
            [(op_type, params, in_shapes, weight_shapes, 0)]
        )

    def family_scale_for(self, fam: str, batch=None) -> float:
        """Fitted residual scale for a family, optionally at a shard
        batch size. A float entry is the constant (geomean) scale; a
        dict entry is the per-batch-REGIME table
        ({"8": s8, "16": s16, ..., "*": geomean}) fitted by
        calibrate.py --fit-family: the conv/attention residual is
        SHAPE-dependent (conv 1.01/1.63/0.82 across bs16/32/64,
        attention 1.46/1.00/1.04 across bs8/16/32 — reproduced across
        rounds 3-5), so a constant can only center the ladder; the
        regime table zeroes each measured point and nearest-bucket
        interpolates between (round-4 VERDICT weak #6 / ask #3)."""
        entry = self._family_scale.get(fam, 1.0)
        if isinstance(entry, dict):
            star = entry.get("*", 1.0)
            if batch is None:
                return float(star) or 1.0
            buckets = [
                (abs(int(k) - batch), float(v))
                for k, v in entry.items()
                if k != "*" and float(v) > 0
            ]
            if not buckets:
                return float(star) or 1.0
            return min(buckets)[1]
        return float(entry) or 1.0

    def dispatch_floor(self) -> float:
        """Per-program overhead baked into every isolated measurement
        (XLA launch + the measurement scan's per-iteration cost),
        measured once per table by timing a compute-free elementwise
        kernel. Sub-ms kernels read as floor + compute in isolation but
        cost only compute inside the real fused step — DLRM's 8 tiny
        MLP matmuls measured ~6x their in-step cost this way (round-4
        VERDICT weak #6 / ask #7). Persisted as "dispatch_floor_s"."""
        if self._dispatch_floor is not None:
            return self._dispatch_floor
        floor = 0.0
        try:
            shape = ParallelTensorShape.make([8, 8], DataType.FLOAT)
            t = self._time_kernel(OperatorType.RELU, {}, [shape], [])
            if t is not None:
                floor = t[0]
        except Exception:
            floor = 0.0
        # contention/slow-clock windows only ever INFLATE the probe, so
        # the min across windows is the honest constant (a 68 us
        # contended reading once priced a 26 us DLRM step at 72 us)
        if self._loaded_floor is not None and self._loaded_floor > 0:
            floor = (
                min(floor, self._loaded_floor)
                if floor > 0
                else self._loaded_floor
            )
        self._dispatch_floor = floor
        if (
            self.calibration_file
            and floor > 0
            and floor != self._loaded_floor  # skip the locked rewrite
        ):
            update_calibration_doc(
                self.calibration_file,
                {"dispatch_floor_s": floor},
                chip=self.spec.chip,
            )
        return floor

    def measured_times_floor_adjusted(
        self, op_type, params, in_shapes, weight_shapes
    ) -> Optional[Tuple[float, float]]:
        """measure_shard minus the dispatch floor, clamped below by the
        analytic roofline (the floor cannot push a time under physics).
        The cache/table keeps RAW measurements; the adjustment applies at
        read so a re-measured floor retroactively corrects old entries."""
        raw = self.measure_shard(op_type, params, in_shapes, weight_shapes)
        if raw is None:
            return None
        fl = self.dispatch_floor()
        if fl <= 0:
            return raw
        f_roof, b_roof = self._shard_roofline_bounds(
            op_type, params, in_shapes, weight_shapes
        )
        return (
            max(f_roof, raw[0] - fl),
            max(b_roof, raw[1] - fl),
        )

    def _shard_roofline_bounds(
        self, op_type, params, in_shapes, weight_shapes
    ) -> Tuple[float, float]:
        """(fwd, bwd) analytic lower bounds for ONE SHARD of the op — the
        clamp under the dispatch-floor subtraction. FLOPs divide by the
        op's output sharding degree (op_flops reads global dim sizes;
        measure_shard times piece shapes — op_cost's own analytic path
        makes the same division); byte terms already use piece sizes. A
        bound that is too LOW only weakens the clamp; one that mixes the
        global basis in would replace shard measurements with up-to-
        degree-times-larger rooflines and bias the search against
        sharded candidates."""
        from flexflow_tpu.ops.registry import infer_shapes

        degree = 1
        try:
            outs, _ = infer_shapes(op_type, list(in_shapes), dict(params))
            if outs:
                degree = max(1, outs[0].total_degree)
        except Exception:
            degree = 1
        flops = op_flops(op_type, in_shapes, params) / degree
        data = sum(self.piece_bytes(s) for s in in_shapes)
        data += sum(self.piece_bytes(s) for s in weight_shapes)
        # TRUE lower bound, not the 0.6-efficiency cost ESTIMATE: a real
        # kernel can beat the estimate (bf16 MXU at high utilization) and
        # a clamp above the measurement would silently replace it
        f_roof = self._roofline(flops, data, efficiency=1.0)
        return f_roof, (2.0 if op_type in _MXU_OPS else 1.0) * f_roof

    def chain_times_floor_adjusted(
        self, specs
    ) -> Optional[Tuple[float, float]]:
        """measure_shard_chain minus ONE dispatch floor (a chain is one
        program), clamped below by the chain's summed roofline."""
        raw = self.measure_shard_chain(specs)
        if raw is None:
            return None
        fl = self.dispatch_floor()
        if fl <= 0:
            return raw
        # conservative (deliberately LOW) fused-program bound: the HEAD
        # op's shard roofline only — the fused epilogue members' bytes
        # stay on-chip, so summing their isolated rooflines could exceed
        # the real fused time and the clamp would inflate the very
        # measurement the chain fix exists to trust
        o, p, ins, ws, _c = specs[0]
        f_roof, b_roof = self._shard_roofline_bounds(o, p, ins, ws)
        return (max(f_roof, raw[0] - fl), max(b_roof, raw[1] - fl))

    def corrected_times(
        self, op_type, times: Optional[Tuple[float, float]], batch=None
    ) -> Optional[Tuple[float, float]]:
        """Divide a measured (fwd, bwd) by the op's fitted family residual
        (constant or batch-regime, family_scale_for). Callers that bypass
        op_cost (the simulator's epilogue-chain measurement — the path
        the conv residual was fitted FOR) must route their raw
        measurements through here too, passing the shard batch when they
        know it."""
        if times is None:
            return times
        fam = op_family(op_type)
        scale = 1.0
        if self.family_correction and fam:
            scale = self.family_scale_for(fam, batch)
        times = (times[0] / scale, times[1] / scale)
        if fam:
            self.family_time[fam] = (
                self.family_time.get(fam, 0.0) + times[0] + times[1]
            )
        return times

    def flush_calibration(self):
        if self.calibration_file:
            self._save_calibration()

    def measure_shard_chain(self, specs) -> Optional[Tuple[float, float]]:
        """Measure a FUSED op chain as one jitted program — the epilogue
        pattern (conv→bn→relu, matmul→add→act) that XLA compiles into one
        kernel. Isolated-op timing structurally over-counts these
        (reference: inner_measure_operator_cost has the same bias,
        model.cu:38-74 — the round-2 ResNet 1.40 pred/meas residual);
        measuring the chain together is the fix.

        specs: [(op_type, params, in_shapes, weight_shapes, chained_idx)]
        where chained_idx says which input of spec i is fed by spec i-1's
        output (ignored for spec 0). Cached/persisted like single ops."""
        if len(specs) == 1:
            # single-op keys keep the historical format so existing
            # calibration tables (calibration/v5e.json) stay valid
            key = self._shard_key(*specs[0][:4])
        else:
            key = "=>".join(
                self._shard_key(o, p, i, w) + f"@{c}"
                for o, p, i, w, c in specs
            )
        if key in self._measured:
            return self._measured[key]
        # single ops go through _time_kernel (the test/monkeypatch seam)
        times = (
            self._time_kernel(*specs[0][:4])
            if len(specs) == 1
            else self._time_kernel_chain(specs)
        )
        self._measured[key] = times
        if self.calibration_file and times is not None:
            # persist immediately: a measurement costs >= _MEASURE_MIN_DIFF_S
            # so the full-file rewrite is noise, and the search engines
            # construct throwaway CostModels that never reach an explicit
            # flush_calibration() (only calibrate.py does) — a throttle
            # here silently dropped their last few measured keys
            self.flush_calibration()
        return times

    def _time_kernel(
        self, op_type, params, in_shapes, weight_shapes
    ) -> Optional[Tuple[float, float]]:
        return self._time_kernel_chain(
            [(op_type, params, in_shapes, weight_shapes, 0)]
        )

    def _time_kernel_chain(self, specs) -> Optional[Tuple[float, float]]:
        op_type = specs[0][0]  # head op classifies the bwd-ratio fallback
        try:
            import time as _time

            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax import lax

            from flexflow_tpu.ops.registry import LowerCtx, lower_op

            lowered = [
                (lower_op(o, p), c) for o, p, _i, _w, c in specs
            ]
            ctx = LowerCtx(
                train=False, rng=None, bf16_matmul=self.mixed_precision
            )

            def arr(s):
                return jnp.full(
                    tuple(
                        d.piece_size
                        for d in s.dims
                        if not d.is_replica_dim
                    ),
                    0.01,
                    s.dtype.to_jnp(),
                )

            # spec 0 takes all its inputs; later specs only their EXTRA
            # inputs (the chained one comes from the previous op)
            ins = []
            ws = []
            for si, (_o, _p, in_shapes_i, w_shapes_i, cidx) in enumerate(
                specs
            ):
                if si == 0:
                    ins.append([arr(s) for s in in_shapes_i])
                else:
                    ins.append(
                        [
                            arr(s)
                            for i, s in enumerate(in_shapes_i)
                            if i != cidx
                        ]
                    )
                ws.append([arr(s) for s in w_shapes_i])

            def as_list(x):
                return list(x) if isinstance(x, (list, tuple)) else [x]

            def perturb_first(arrs, seed):
                # perturb the first float array by a vanishing function of
                # the previous iteration's result: forces true iteration
                # dependence without changing the math measurably
                out = list(arrs)
                for i, a in enumerate(out):
                    if jnp.issubdtype(a.dtype, jnp.floating):
                        out[i] = a * (1.0 + seed * 1e-30).astype(a.dtype)
                        return out, True
                return out, False

            def apply_op(inputs, weights, seed):
                pins, done = perturb_first(inputs[0], seed)
                pws0 = list(weights[0])
                if not done:
                    pws0, _ = perturb_first(weights[0], seed)
                out = None
                outs = []
                for si, (fn, cidx) in enumerate(lowered):
                    if si == 0:
                        ins_i, ws_i = pins, pws0
                    else:
                        ins_i = list(inputs[si])
                        ins_i.insert(cidx, out)
                        ws_i = list(weights[si])
                    outs = as_list(fn(ins_i, ws_i, ctx))
                    out = outs[0]
                tot = jnp.float32(0.0)
                for o in outs:  # the chain's FINAL outputs
                    tot = tot + jnp.sum(o.astype(jnp.float32))
                return tot

            k = self._MEASURE_CHAIN
            # differentiable leaves: the head's float inputs + all
            # weights (integer inputs — embedding ids — are closed over;
            # later specs' extra inputs likewise stay constants)
            fidx = [
                i
                for i, a in enumerate(ins[0])
                if jnp.issubdtype(a.dtype, jnp.floating)
            ]
            flat_ws = [w for per in ws for w in per]
            w_split = np.cumsum([len(per) for per in ws]).tolist()

            def unflatten_ws(flat):
                out, start = [], 0
                for end in w_split:
                    out.append(list(flat[start:end]))
                    start = end
                return out

            def fwd_chain(inputs, weights):
                def body(s, _):
                    return (
                        apply_op(inputs, unflatten_ws(weights), s) * 1e-30,
                        None,
                    )

                s, _ = lax.scan(
                    body, jnp.float32(0.0), None, length=k
                )
                return s

            def bwd_chain(inputs, weights):
                def body(s, _):
                    def loss(args):
                        flt, w2 = args
                        pins = [list(p) for p in inputs]
                        for j, i2 in enumerate(fidx):
                            pins[0][i2] = flt[j]
                        return apply_op(pins, unflatten_ws(list(w2)), s)

                    val, grads = jax.value_and_grad(loss)(
                        (
                            tuple(inputs[0][i] for i in fidx),
                            tuple(weights),
                        )
                    )
                    acc = val
                    for leaf in jax.tree_util.tree_leaves(grads):
                        acc = acc + jnp.sum(leaf.astype(jnp.float32))
                    return acc * 1e-30, None

                s, _ = lax.scan(
                    body, jnp.float32(0.0), None, length=k
                )
                return s

            def timed(jitted):
                out = jitted(ins, flat_ws)  # compile + warmup
                float(np.asarray(out))

                def run(n):
                    t0 = _time.perf_counter()
                    for _ in range(n):
                        out = jitted(ins, flat_ws)
                    float(np.asarray(out))  # forces the whole chain
                    return _time.perf_counter() - t0

                n = 2
                while True:
                    t1 = run(n)
                    t2 = run(2 * n)
                    diff = t2 - t1
                    if (
                        diff > self._MEASURE_MIN_DIFF_S
                        or n >= self._MEASURE_MAX_CALLS
                    ):
                        break
                    # jump straight to a count that should clear the bar
                    grow = self._MEASURE_MIN_DIFF_S / max(diff, 1e-4)
                    n = min(
                        max(2 * n, int(n * grow) + 1),
                        self._MEASURE_MAX_CALLS,
                    )
                per_iter = diff / (n * k)
                return max(per_iter, 1e-9)

            fwd = timed(jax.jit(fwd_chain))
            if fwd > 0.1:
                # no single-op/chain shard at search scale runs 100 ms
                # (the largest legit table entry is ~20 ms) — this is
                # tunnel contention (another process holding the device);
                # don't poison the table. A contended 119 ms conv+bn
                # entry once multiplied into a 2.1 s ResNet prediction
                # through shape-signature reuse.
                return None
            if fwd < 1e-7:
                # below the differencing noise floor: a negative or ~zero
                # window means the measurement failed — do not poison the
                # cache/table with it (roofline fallback instead)
                return None
            if not fidx and not flat_ws:
                return (fwd, fwd)  # nothing differentiable: estimate
            total = timed(jax.jit(bwd_chain))
            if total > 0.3:
                return None  # contended during the backward window
            bwd = total - fwd
            if bwd < 0.5 * fwd:
                # bwd can't be cheaper than re-running forward; a smaller
                # difference is noise — substitute the analytic ratio
                bwd = (2.0 if op_type in _MXU_OPS else 1.0) * fwd
            return (fwd, bwd)
        except Exception:
            import os

            if os.environ.get("FFTPU_MEASURE_DEBUG"):
                raise  # surface the real error instead of a None fallback
            return None

    # -- optimizer update ----------------------------------------------------

    def update_traffic_factor(self, state_factor: float = 3.0) -> float:
        """Bytes multiplier of one optimizer update: read w + read g +
        r/w each state slot + write w = 2·state_factor − 1. THE shared
        constant — unity.py and native/src/unity_dp.cc receive it from
        here so every engine prices updates identically."""
        return 2.0 * state_factor - 1.0

    def update_time_from_bytes(
        self, weight_bytes: float, state_factor: float = 3.0
    ) -> float:
        """THE optimizer-update HBM-time formula, shared by every engine
        (mesh estimator, unity Python DP; the native solver receives the
        factor and the same effective bandwidth). weight_bytes are
        MASTER-precision bytes — optimizer state and the update walk stay
        f32 under mixed precision."""
        traffic = self.update_traffic_factor(state_factor) * weight_bytes
        return traffic / (self.spec.hbm_gbps * 1e9 * self.efficiency)

    def update_cost(
        self, weight_shape: ParallelTensorShape, state_factor: float = 3.0
    ) -> float:
        """HBM time of one parameter's optimizer update (reference models
        update tasks in its task graph, simulator.cc:810+; the NCCL/PS sync
        is costed separately)."""
        return self.update_time_from_bytes(
            weight_shape.piece_bytes(), state_factor
        )

    def sparse_embedding_op_cost(
        self, weight_shape, rows_per_step: float
    ) -> Tuple[float, float]:
        """(fwd_s, bwd_s) of an embedding on the executor's sparse fast
        path: forward gathers the batch's rows, backward builds only the
        touched-row gradient (Executor._sparse_embedding_guids never
        materializes a table-sized gradient). The measured-mode kernel
        times the registry lowering's DENSE-gradient VJP instead — wrong
        by the table/batch ratio (a 4x1M-table DLRM mis-predicts ~500x on
        the measured basis), so sparse-eligible embeddings must take this
        analytic path even in measured mode."""
        dim = weight_shape.dims[-1].piece_size
        elem = self.elem_bytes(weight_shape)
        bytes_rw = rows_per_step * dim * elem
        t = bytes_rw / (self.spec.hbm_gbps * 1e9 * self.efficiency)
        # backward touches the same rows twice (zero-init + scatter-add)
        return (t, 2.0 * t)

    def sparse_sync_cost(
        self, row_bytes_per_chip: float, group_size: int, chips=None
    ) -> float:
        """Touched-row broadcast for a sparse-eligible table whose replicas
        span `group_size` chips while the ids/cotangents are batch-sharded
        across them: GSPMD lowers the scatter-update into an all-gather of
        the (ids, rows) pairs so every replica applies the full scatter
        (Executor.sparse_step's sparse_row_update under jit). Tiny next to
        the table-sized all-reduce the fast path eliminates, but real —
        without it, dp-replicated tables would look literally free to keep
        consistent (round-5 reconciliation of the bba35f9 sparse pricing)."""
        return self.all_gather(row_bytes_per_chip, group_size, chips=chips)

    def sparse_update_cost(
        self,
        weight_shape: ParallelTensorShape,
        rows_per_step: float,
        state_factor: float = 3.0,
    ) -> float:
        """Optimizer update of a sparse-eligible embedding table
        (Executor._sparse_embedding_guids): only the batch's touched rows
        move, so traffic is rows x dim, not vocab x dim — the term that
        makes the measured 587x DLRM update win visible to the search.
        Master-precision bytes, like update_cost."""
        dim = weight_shape.dims[-1].piece_size
        elem = weight_shape.dtype.size_bytes
        return self.update_time_from_bytes(
            rows_per_step * dim * elem, state_factor
        )

    # -- calibration-table persistence --------------------------------------

    def _load_calibration(self):
        import json
        import os
        import warnings

        if not os.path.exists(self.calibration_file):
            return
        try:
            with open(self.calibration_file) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        table_chip = doc.get("chip")
        if table_chip and table_chip != self.spec.chip:
            warnings.warn(
                f"calibration table {self.calibration_file} was measured "
                f"on chip {table_chip!r} but this search targets "
                f"{self.spec.chip!r}; ignoring the table",
                stacklevel=2,
            )
            return
        for key, val in doc.get("ops", {}).items():
            if val:  # failed measurements (null) are never persisted/read
                self._measured[key] = tuple(val)
        fl = doc.get("dispatch_floor_s")
        if isinstance(fl, (int, float)) and fl >= 0:
            self._loaded_floor = float(fl)
        for fam, scale in doc.get("family_scale", {}).items():
            if isinstance(scale, (int, float)) and scale > 0:
                self._family_scale[fam] = float(scale)
            elif isinstance(scale, dict) and scale:
                # per-batch-regime table (family_scale_for)
                clean = {
                    str(k): float(v)
                    for k, v in scale.items()
                    if isinstance(v, (int, float)) and v > 0
                }
                if clean:
                    self._family_scale[fam] = clean

    def _save_calibration(self):
        # merged write (update_calibration_doc): other writers own sibling
        # keys (flash_blocks from --tune-flash, family_scale from
        # --fit-family) and a measured-search flush must not clobber them;
        # a foreign-chip doc is dropped rather than relabeled
        update_calibration_doc(
            self.calibration_file,
            {
                "ops": {
                    key: list(val)
                    for key, val in self._measured.items()
                    if val is not None
                }
            },
            chip=self.spec.chip,
        )
