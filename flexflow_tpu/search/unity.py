"""Unity-style DP search over per-op MachineViews.

TPU rebuild of the reference's Unity dynamic-programming search
(reference: SearchHelper::graph_cost, src/runtime/graph.cc:1346-1431;
sequence/nonsequence splits graph.cc:93-306; machine-view enumeration
graph.cc:1783-1814; memoization by dp_state_hash graph.cc:1531-1543):

  * **sequence split**: find a bottleneck node (a node on every path from
    the subgraph's sources to its sink, located via immediate
    post-dominators like the reference's find_split_node,
    substitution.cc:1984); enumerate its valid machine views; recurse on
    the two halves with the bottleneck's view fixed at the boundary.
  * **nonsequence split**: no bottleneck ⇒ the subgraph is parallel
    branches; try running the branches concurrently on vertical /
    horizontal resource splits (reference: MachineResource::vertical(i)/
    horizontal(i), graph.cc:252-306) or sequentially on the full
    resources; take the min.
  * **leaf**: one node — roofline op cost on the view's shard + transfer
    cost for re-laying the producer's output onto this view + gradient
    all-reduce over the view's data replicas (the reference's NCCL
    allreduce term, optimizer_kernel.cu:88).
  * memoized by (subgraph, boundary views, resource block).

Views live on the abstract chip grid the way the reference's do
({start, dims, strides}); lowering restricts to mesh-expressible
assignments (SURVEY §7's documented v1 restriction): the per-node views
are reduced to one global (data × model) mesh and the tensor-parallel
rewrite sites whose ops the search gave a 2-D view. The full per-op view
map is still exported via --export-strategy for inspection, mirroring the
reference's per-op ParallelConfig strategy files (strategy.cc:100-197).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineResource, MachineSpec, MachineView
from flexflow_tpu.core.parallel_tensor import ParallelTensorShape
from flexflow_tpu.core.pcg import PCGGraph, trace_embedding_ids_input
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.ops.registry import op_flops
from flexflow_tpu.search.cost_model import CostModel

# ops that may take a 2-D (data × channel) view: the second view dim
# partitions output channels / heads / embedding columns (reference:
# Linear::get_random_parallel_config explores exactly these grids,
# linear.cc:707-744; DLRM shards embedding tables, embedding.cc)
_CHANNEL_OPS = {
    OperatorType.LINEAR,
    OperatorType.MULTIHEAD_ATTENTION,
    OperatorType.EMBEDDING,
    OperatorType.CONV2D,
}


def _node_channel_size(node) -> Optional[int]:
    if node.op_type == OperatorType.LINEAR:
        return node.params.get("out_features")
    if node.op_type == OperatorType.MULTIHEAD_ATTENTION:
        return node.params.get("num_heads")
    if node.op_type == OperatorType.EMBEDDING:
        return node.params.get("out_dim")
    if node.op_type == OperatorType.CONV2D:
        return node.params.get("out_channels")
    return None


def _batch_size(node) -> int:
    shape = node.output_shapes[0] if node.output_shapes else None
    if shape is None:
        return 1
    logical = [d for d in shape.dims if not d.is_replica_dim]
    return logical[0].size if logical else 1


@dataclasses.dataclass(frozen=True)
class ViewOption:
    """A machine view plus its logical factorization: `dp` devices partition
    the sample dim, `ch` partition channels/heads (dp * ch == devices).
    The reference encodes this positionally in ParallelConfig.dim[]
    (machine_view.h:62-96); keeping it explicit avoids conflating the
    device geometry (node-major grid) with the tensor mapping."""

    view: MachineView
    dp: int
    ch: int = 1

    @property
    def num_devices(self) -> int:
        return self.view.num_devices

    def key(self) -> Tuple[int, int, int]:
        return (self.view.hash(), self.dp, self.ch)


@dataclasses.dataclass
class UnityResult:
    cost: float
    views: Dict[int, ViewOption]  # guid -> chosen option

    def describe(self) -> str:
        grids = Counter((v.dp, v.ch) for v in self.views.values())
        return (
            f"unity: simulated step {self.cost * 1e3:.3f} ms, "
            f"(dp, ch) grids {dict(grids)}"
        )


class UnitySearch:
    """One search instance per (graph, machine). Graph must have inferred
    output shapes (propagate_shapes) with NO strategy applied — views carry
    the parallelism."""

    def __init__(
        self,
        graph: PCGGraph,
        spec: MachineSpec,
        resource: Optional[MachineResource] = None,
        include_backward: bool = True,
        machine_model=None,
        mixed_precision: bool = False,
        measure: bool = False,
        calibration_file: str = "",
        sparse_embedding: bool = True,
        allow_subblock_views: bool = False,
        trace=None,
    ):
        """allow_subblock_views: let the nonsequence (parallel-branch)
        recursion place concurrent branches on vertical/horizontal
        resource SUB-blocks (reference: graph.cc:252-306). The v1
        lowering collapses every view to ONE global mesh, which executes
        branches sequentially — so with sub-block views on, the DP can
        return a cost predicated on a placement the executor cannot
        honor (the round-2 search-cost/lowering divergence for branchy
        graphs). Default OFF: the returned cost equals the simulated
        cost of the strategy actually lowered
        (tests/test_branchy_cost.py). Turn on only for search-space
        studies / strategy export. The executable primitive for
        concurrent branches exists (parallel/submesh.concurrent_branches
        — shard_map + lax.switch over a block axis, SPMD-restricted to
        shape-unified branches); wiring it into the PCG lowering is
        future work.

        trace: an optional telemetry.SearchTrace — every (node, view)
        leaf cost the DP evaluates is recorded once (tagged measured /
        analytic / sparse), plus the search phases and the winning
        per-op breakdown (the explain-report artifact)."""
        self.graph = graph
        self.trace = trace
        self._trace_seen = set()
        self.allow_subblock_views = allow_subblock_views
        self.spec = spec
        self.cm = CostModel(
            spec,
            machine_model=machine_model,
            mixed_precision=mixed_precision,
            measure=measure,
            calibration_file=calibration_file,
            sparse_embedding=sparse_embedding,
        )
        self.resource = resource or spec.resource()
        self.include_backward = include_backward
        self._memo: Dict[Tuple, Tuple[float, Dict[int, ViewOption]]] = {}
        self._views_cache: Dict[Tuple[int, Tuple], List[ViewOption]] = {}
        self._ubytes_cache: Dict[int, Tuple[float, bool]] = {}
        self.memo_hits = 0

    # -- view enumeration ----------------------------------------------------

    def _block_view(
        self, resource: MachineResource, n: int
    ) -> Optional[MachineView]:
        """n devices of the resource block in node-major order; None when n
        does not tile the block. Views never spill outside their block —
        MachineResource.is_valid_view holds by construction (the reference
        checks it per view, machine_view.h:51-60)."""
        cpn = resource.chips_per_node
        start = (
            resource.start_node_id * self.spec.chips_per_node
            + resource.start_chip_id
        )
        if n <= cpn:
            return MachineView(start, (n,), (1,))
        if n % cpn == 0 and n // cpn <= resource.num_nodes:
            return MachineView(
                start, (n // cpn, cpn), (self.spec.chips_per_node, 1)
            )
        return None

    def valid_views(
        self, guid: int, resource: MachineResource
    ) -> List[ViewOption]:
        """reference: get_valid_machine_views (graph.cc:503+) filtering
        register_all_machine_views; starts are canonicalized to the resource
        block's origin — TPU slices are symmetric, so shifted views cost the
        same and would only bloat the memo."""
        key = (
            guid,
            (resource.num_nodes, resource.chips_per_node, resource.start_chip_id,
             resource.start_node_id),
        )
        if key in self._views_cache:
            return self._views_cache[key]
        node = self.graph.nodes[guid]
        total = resource.num_chips
        batch = _batch_size(node)
        chan = _node_channel_size(node)
        views: List[ViewOption] = []
        for n in range(1, total + 1):
            if total % n != 0:
                continue
            mv = self._block_view(resource, n)
            if mv is None:
                continue
            if batch % n == 0:
                views.append(ViewOption(mv, dp=n, ch=1))
            if chan is not None and node.op_type in _CHANNEL_OPS:
                for dp in range(1, n + 1):
                    if n % dp != 0:
                        continue
                    ch = n // dp
                    if ch > 1 and batch % dp == 0 and chan % ch == 0:
                        views.append(ViewOption(mv, dp=dp, ch=ch))
        if not views:
            views.append(ViewOption(self._block_view(resource, 1), dp=1, ch=1))
        self._views_cache[key] = views
        return views

    # -- per-(node, view) costs ---------------------------------------------

    def _measured_times(
        self, node, in_shapes, opt: ViewOption
    ) -> Optional[Tuple[float, float]]:
        """(fwd, bwd) of the real jitted kernel on the shard this view
        gives one chip (reference: measure_operator_cost at the search's
        leaves, simulator.cc:532). dp shards the batch dim; ch shards
        Linear output channels exactly (params rewrite + re-infer) and MHA
        heads approximately (full-head shard measured, time / ch — head
        shards are the same matmuls at 1/ch width)."""
        from flexflow_tpu.ops.registry import infer_shapes
        from flexflow_tpu.search.cost_model import _MEASURED_OPS

        if node.op_type not in _MEASURED_OPS:
            return None
        try:
            shard_ins = []
            for s in in_shapes:
                sizes = list(s.logical_sizes)
                if opt.dp > 1:
                    if not sizes or sizes[0] % opt.dp != 0:
                        return None
                    sizes[0] //= opt.dp
                shard_ins.append(
                    ParallelTensorShape.make(sizes, s.dtype)
                )
            params = dict(node.params)
            divide = 1
            if opt.ch > 1:
                if (
                    node.op_type == OperatorType.LINEAR
                    and params.get("out_features", 0) % opt.ch == 0
                ):
                    params["out_features"] //= opt.ch
                elif (
                    node.op_type == OperatorType.EMBEDDING
                    and params.get("out_dim", 0) % opt.ch == 0
                ):
                    params["out_dim"] //= opt.ch
                elif (
                    node.op_type == OperatorType.CONV2D
                    and params.get("out_channels", 0) % opt.ch == 0
                ):
                    params["out_channels"] //= opt.ch
                else:
                    divide = opt.ch
            _, ws = infer_shapes(node.op_type, shard_ins, params)
            # corrected_times: the fitted family residual must divide
            # every raw measurement consumer, or unity/mcmc (and the
            # native DP LUT built from this) would rank cross-family
            # candidates with the bias the correction removes
            from flexflow_tpu.search.cost_model import shard_batch

            times = self.cm.corrected_times(
                node.op_type,
                self.cm.measured_times_floor_adjusted(
                    node.op_type, params, shard_ins, ws
                ),
                batch=shard_batch(shard_ins),
            )
            if times is None:
                return None
            return (times[0] / divide, times[1] / divide)
        except Exception:
            return None

    def _sparse_embedding_time(self, guid, node, opt):
        """Fwd(+bwd) seconds for a SPARSE-eligible embedding under `opt`,
        else None. The executor's fast path gathers/scatters touched rows
        only — neither the measured dense-grad kernel nor the table
        roofline applies (same basis as simulator.estimate_graph_cost and
        _update_bytes; the round-4 DLRM 490x finding). Shared by op_cost
        and the native-solver LUT builder so both engines price it
        identically."""
        if node.op_type != OperatorType.EMBEDDING or not node.weight_shapes:
            return None
        _ub, rows = self._update_bytes(guid)
        if rows is None:
            return None
        # rows shard over dp (batch), the row dim over ch: the rows x dim
        # product divides by dp*ch either way
        f, b = self.cm.sparse_embedding_op_cost(
            node.weight_shapes[0], rows / (opt.dp * opt.ch)
        )
        return f + (b if self.include_backward else 0.0)

    def op_cost(self, guid: int, opt: ViewOption) -> float:
        """Fwd(+bwd) seconds of the node's shard under `opt`: the real
        measured kernel when the cost model is in measured mode
        (reference: simulator.cc:532), the analytic roofline otherwise."""
        node = self.graph.nodes[guid]
        if node.op_type == OperatorType.INPUT or node.is_parallel_op:
            return 0.0
        n = opt.num_devices
        in_shapes = [self.graph.shape_of(r) for r in node.inputs]
        eb = self.cm.elem_bytes
        # sparse-eligible embeddings price compute analytically but FALL
        # THROUGH to the sync/update section below: the no-all-reduce and
        # touched-rows-update terms there (and in the native solver's
        # ubytes arrays) still apply
        t = self._sparse_embedding_time(guid, node, opt)
        source = "sparse" if t is not None else "analytic"
        if t is None and self.cm.measure:
            mt = self._measured_times(node, in_shapes, opt)
            if mt is not None:
                t = mt[0] + (mt[1] if self.include_backward else 0.0)
                source = "measured"
        if t is None:
            flops = op_flops(node.op_type, in_shapes, node.params) / n
            data = sum(s.volume() * eb(s) for s in in_shapes)
            data += sum(s.volume() * eb(s) for s in node.output_shapes)
            data += sum(s.volume() * eb(s) for s in node.weight_shapes)
            t = self.cm._roofline(flops, data / n)
            if self.include_backward:
                mxu = (
                    node.op_type in _CHANNEL_OPS
                    or node.op_type == OperatorType.BATCHMATMUL
                )
                t *= 3.0 if mxu else 2.0
        # gradient sync: weights are sharded ch ways and replicated across
        # the dp data replicas; all-reduce the shards over the actual device
        # ids of one replica group (ids are laid out (dp, ch) row-major, so
        # a group is every ch-th device — possibly crossing nodes)
        if self.include_backward and node.weight_shapes:
            ub, sparse_rows = self._update_bytes(guid)
            group = opt.view.device_ids()[:: opt.ch]
            if sparse_rows is None:
                # the sparse fast path never materializes a table-sized
                # gradient, so eligible tables pay NO grad all-reduce —
                # matching simulator.estimate_graph_cost's basis exactly
                w_bytes = (
                    sum(s.volume() * eb(s) for s in node.weight_shapes)
                    / opt.ch
                )
                t += self.cm.all_reduce(w_bytes, opt.dp, chips=group)
            else:
                # the dp replicas must still exchange touched rows
                # (batch-sharded ids scatter into a shared table): an
                # all-gather of rows x dim over the dp group
                t += self.cm.sparse_sync_cost(
                    ub / (opt.dp * opt.ch), opt.dp, chips=group
                )
            # optimizer update traffic (CostModel.update_time_from_bytes,
            # the same formula/basis as estimate_graph_cost): without it
            # the engines' absolute step times are not comparable to the
            # mesh candidates and weight-heavy dp looks free
            per_chip = ub / opt.ch / (opt.dp if sparse_rows is not None else 1)
            t += self.cm.update_time_from_bytes(per_chip)
        if self.trace is not None:
            self._trace_leaf("op_view", guid, opt, t, source)
        return t

    def _trace_leaf(
        self, kind: str, guid: int, opt: ViewOption, cost: float, source: str
    ) -> None:
        """Record one (node, view) leaf evaluation — once per key (the
        memoless DP re-evaluates leaves constantly). Only precomputed
        scalars cross into the record: trace rows must never hold live
        graph/search state (fxlint FX104)."""
        key = (kind, guid, opt.key())
        if key in self._trace_seen:
            return
        self._trace_seen.add(key)
        node = self.graph.nodes[guid]
        op_name = node.name
        op_type = node.op_type.name
        self.trace.candidate(
            kind,
            source=source,
            guid=guid,
            name=op_name,
            op=op_type,
            dp=opt.dp,
            ch=opt.ch,
            cost=cost,
        )

    def _trace_result(self, result: "UnityResult", path_kind: str) -> None:
        """Record the winning strategy with its per-op breakdown. The
        residual (DP concurrency credit, dispatch floor) is defined as
        total minus the in-order breakdown sum, so the explain report
        reconstructs `result.cost` exactly by inverting the
        subtraction."""
        ops = []
        listed = 0.0
        for guid in sorted(result.views):
            node = self.graph.nodes.get(guid)
            if node is None:
                continue
            v = result.views[guid]
            oc = self.op_cost(guid, v)
            xc = 0.0
            for r in node.inputs:
                src = result.views.get(r.guid)
                if src is not None:
                    xc += self.xfer_cost(r, src, v)
            op_name = node.name
            op_type = node.op_type.name
            ops.append(
                {
                    "guid": guid,
                    "name": op_name,
                    "op": op_type,
                    "dp": v.dp,
                    "ch": v.ch,
                    "op_cost": oc,
                    "xfer_cost": xc,
                }
            )
            listed += oc + xc
        grids = Counter((v.dp, v.ch) for v in result.views.values())
        self.trace.result(
            total_cost=result.cost,
            ops=ops,
            residual=result.cost - listed,
            path=path_kind,
            grids={f"dp{d}xch{c}": n for (d, c), n in sorted(grids.items())},
        )

    def _update_bytes(self, guid: int) -> Tuple[float, Optional[float]]:
        """(bytes basis, touched rows | None) for the optimizer-update
        term: full MASTER-precision weight bytes normally (optimizer state
        is f32 under mixed precision — matching CostModel.update_cost's
        piece_bytes basis); touched-rows bytes for tables on the sparse
        fast path (core.pcg.trace_embedding_ids_input — rows follow the
        batch sharding, hence the dp division). The row count rides along
        so consumers never invert the byte formula (ADVICE r4: one
        formula, not a formula and its hand-written inverse). Per-guid
        constant, cached."""
        hit = self._ubytes_cache.get(guid)
        if hit is not None:
            return hit
        node = self.graph.nodes[guid]
        out: Tuple[float, Optional[float]]
        ref = (
            trace_embedding_ids_input(self.graph, guid)
            if self.cm.sparse_embedding
            else None
        )
        if ref is not None:
            ids_shape = self.graph.shape_of(ref)
            w = node.weight_shapes[0]
            rows = float(ids_shape.volume())
            out = (
                rows * w.dims[-1].size * w.dtype.size_bytes,
                rows,
            )
        else:
            out = (
                float(
                    sum(
                        s.volume() * s.dtype.size_bytes
                        for s in node.weight_shapes
                    )
                ),
                None,
            )
        self._ubytes_cache[guid] = out
        return out

    def xfer_cost(self, ref, src: ViewOption, dst: ViewOption) -> float:
        """Re-layout cost of one tensor between views (reference:
        estimate_xfer_cost, graph.cc:1291 → simulator.cc:617)."""
        if src.key() == dst.key():
            return 0.0
        shape = self.graph.shape_of(ref)
        bytes_total = shape.volume() * self.cm.elem_bytes(shape)
        n = max(src.num_devices, dst.num_devices)
        return self.cm.all_to_all(bytes_total / dst.num_devices, n)

    # -- the DP ---------------------------------------------------------------

    def optimize(self) -> UnityResult:
        """Full-graph entry: enumerate sink views, run the DP
        (reference: Graph::optimal_cost, graph.cc:1433). Single-sink
        graphs on the flat machine model run the NATIVE C++ solver
        (native/src/unity_dp.cc — SURVEY §7's prescription that the
        compute-bound tree search be native); everything else uses the
        Python recursion with identical semantics."""
        result, path_kind = self._optimize_inner()
        if self.cm.measure:
            # one program launch per step — the same basis term
            # estimate_graph_cost adds, so the cross-engine gate in
            # auto.search_strategy compares like with like
            result = UnityResult(
                result.cost + self.cm.dispatch_floor(), result.views
            )
        if self.trace is not None:
            self._trace_result(result, path_kind)
        return result

    def _optimize_inner(self) -> Tuple[UnityResult, str]:
        from contextlib import nullcontext

        from flexflow_tpu import native as native_mod

        def _phase(name):
            return (
                self.trace.phase(name)
                if self.trace is not None
                else nullcontext()
            )

        sinks = self.graph.sinks()
        if (
            len(sinks) == 1
            and self.cm.machine_model is None
            and self.include_backward
            # guard BEFORE the per-node extraction pass: without the
            # library (or past the 256-node bitset cap) the pass would be
            # wasted and redone by the Python path
            and len(self.graph.nodes) <= 256
            and native_mod.get_lib() is not None
        ):
            # measured mode pre-resolves every (node, view) leaf cost with
            # the real calibrated kernels, then hands the table to the
            # native solver — the calibration table and the 33x native
            # solver compose (VERDICT r2 item 9)
            with _phase("unity:measured_lut" if self.cm.measure
                        else "unity:native_prep"):
                lut = self._measured_lut() if self.cm.measure else None
            with _phase("unity:native_dp"):
                native_result = self._optimize_native(sinks[0], measured=lut)
            if native_result is not None:
                return native_result, "native"
        with _phase("unity:python_dp"):
            return self._optimize_python(sinks), "python"

    def _measured_lut(self):
        """{guid: [(dp, ch, fwd+bwd seconds)]} for every node/view the
        solver can choose, from the calibrated kernel measurements
        (reference: simulator.cc:532 measured leaves). Entries that fail
        to measure fall back to the native roofline (absent from the
        LUT)."""
        lut = {}
        full = self.resource
        for guid in self.graph.topo_order():
            node = self.graph.nodes[guid]
            if node.op_type == OperatorType.INPUT or node.is_parallel_op:
                continue
            in_shapes = [self.graph.shape_of(r) for r in node.inputs]
            entries = []
            for opt in self.valid_views(guid, full):
                st = self._sparse_embedding_time(guid, node, opt)
                if st is not None:
                    entries.append((opt.dp, opt.ch, st))
                    if self.trace is not None:
                        self._trace_leaf("lut_entry", guid, opt, st, "sparse")
                    continue
                mt = self._measured_times(node, in_shapes, opt)
                if mt is None:
                    continue
                cost = mt[0] + (mt[1] if self.include_backward else 0.0)
                entries.append((opt.dp, opt.ch, cost))
                if self.trace is not None:
                    self._trace_leaf("lut_entry", guid, opt, cost, "measured")
            if entries:
                lut[guid] = entries
        return lut

    def _optimize_native(
        self, sink: int, measured=None
    ) -> Optional[UnityResult]:
        from flexflow_tpu import native
        from flexflow_tpu.search.cost_model import (
            _DEFAULT_EFFICIENCY as EFF,
            _ICI_LATENCY_S as LAT,
        )

        guids = sorted(self.graph.nodes)
        index = {g: i for i, g in enumerate(guids)}
        batch, chan, flops, bytes_moved, wbytes, bwd = [], [], [], [], [], []
        ubytes, u_dp_scaled, sbytes = [], [], []
        edges = []
        eb = self.cm.elem_bytes  # byte counts reach the solver pre-scaled,
        # so the native path is dtype/mixed-precision aware for free and the
        # Python↔native bit-equivalence is preserved by construction
        for g in guids:
            node = self.graph.nodes[g]
            batch.append(_batch_size(node))
            is_chan = node.op_type in _CHANNEL_OPS
            chan.append(_node_channel_size(node) or -1 if is_chan else -1)
            in_shapes = [self.graph.shape_of(r) for r in node.inputs]
            if node.op_type == OperatorType.INPUT or node.is_parallel_op:
                flops.append(0.0)
                bytes_moved.append(0.0)
                wbytes.append(0.0)
                bwd.append(0.0)
                ubytes.append(0.0)
                u_dp_scaled.append(0)
                sbytes.append(0.0)
            else:
                flops.append(op_flops(node.op_type, in_shapes, node.params))
                data = sum(s.volume() * eb(s) for s in in_shapes)
                data += sum(s.volume() * eb(s) for s in node.output_shapes)
                data += sum(s.volume() * eb(s) for s in node.weight_shapes)
                bytes_moved.append(data)
                mxu = is_chan or node.op_type in (
                    OperatorType.CONV2D,
                    OperatorType.BATCHMATMUL,
                )
                bwd.append(3.0 if mxu else 2.0)
                if node.weight_shapes:
                    ub, sparse_rows = self._update_bytes(g)
                    sparse = sparse_rows is not None
                    ubytes.append(ub)
                    u_dp_scaled.append(1 if sparse else 0)
                    # sparse-eligible tables never materialize a grad:
                    # no all-reduce term (wbytes drives sync in the
                    # native op_cost, unity_dp.cc) — but the dp replicas
                    # all-gather the touched rows (sbytes, same term as
                    # op_cost's sparse_sync_cost)
                    wbytes.append(
                        0.0
                        if sparse
                        else sum(
                            s.volume() * eb(s) for s in node.weight_shapes
                        )
                    )
                    sbytes.append(ub if sparse else 0.0)
                else:
                    ubytes.append(0.0)
                    u_dp_scaled.append(0)
                    wbytes.append(0.0)
                    sbytes.append(0.0)
            for r in node.inputs:
                if r.guid in index:
                    shape = self.graph.shape_of(r)
                    edges.append(
                        (
                            index[r.guid],
                            index[g],
                            shape.volume() * eb(shape),
                        )
                    )
        out = native.unity_dp(
            edges,
            batch,
            chan,
            flops,
            bytes_moved,
            wbytes,
            bwd,
            self.resource.num_nodes,
            self.resource.chips_per_node,
            self.spec.peak_tflops * 1e12 * EFF,
            self.spec.hbm_gbps * 1e9 * EFF,
            self.spec.ici_gbps * 1e9 * EFF,
            LAT,
            index[sink],
            ubytes=ubytes,
            u_dp_scaled=u_dp_scaled,
            sbytes=sbytes,
            update_factor=self.cm.update_traffic_factor(),
            allow_subblock=self.allow_subblock_views,
            measured=[
                (index[g], dp, ch, cost)
                for g, entries in (measured or {}).items()
                for dp, ch, cost in entries
            ],
        )
        if out is None:
            return None
        cost, dps, chs = out
        views: Dict[int, ViewOption] = {}
        for g, dp, ch in zip(guids, dps, chs):
            n = dp * ch
            # canonical full-resource geometry; a count chosen on a
            # concurrent sub-block may not tile the full block — fall back
            # to a plain 1-D strided view (placement detail is dropped; the
            # (dp, ch) factorization, which lowering consumes, is exact)
            mv = self._block_view(self.resource, n) or MachineView(
                0, (n,), (1,)
            )
            views[g] = ViewOption(mv, dp=dp, ch=ch)
        return UnityResult(cost, views)

    def _optimize_python(self, sinks) -> UnityResult:
        if len(sinks) != 1:
            # multiple sinks (rare; metrics heads): cost the largest
            # subgraph first, then only each later sink's EXCLUSIVE nodes —
            # shared-trunk nodes keep their first assignment and are not
            # double-counted. Boundary transfers from the trunk into the
            # exclusive tail are not charged (documented approximation).
            anc_of = {
                s: set(self.graph.ancestors_of([s])) for s in sinks
            }  # ancestors_of includes the start node itself
            order = sorted(sinks, key=lambda s: len(anc_of[s]), reverse=True)
            views: Dict[int, ViewOption] = {}
            total = 0.0
            covered: set = set()
            for s in order:
                anc = anc_of[s]
                # a sink is nobody's ancestor, so s is always in `exclusive`
                exclusive = frozenset(anc - covered)
                best = None
                for view in self.valid_views(s, self.resource):
                    c, v = self._graph_cost(
                        exclusive, None, s, view, self.resource
                    )
                    if best is None or c < best[0]:
                        best = (c, {**v, s: view})
                total += best[0]
                for g, v in best[1].items():
                    views.setdefault(g, v)
                covered |= anc
            return UnityResult(total, views)
        return self._best_for_sink(sinks[0])

    def _best_for_sink(self, sink: int) -> UnityResult:
        sub = frozenset(self.graph.ancestors_of([sink])) | {sink}
        best: Optional[Tuple[float, Dict[int, ViewOption]]] = None
        for view in self.valid_views(sink, self.resource):
            c, v = self._graph_cost(sub, None, sink, view, self.resource)
            if best is None or c < best[0]:
                best = (c, {**v, sink: view})
        assert best is not None
        return UnityResult(best[0], best[1])

    def _res_key(self, r: MachineResource):
        return (r.num_nodes, r.chips_per_node, r.start_node_id, r.start_chip_id)

    def _graph_cost(
        self,
        sub: FrozenSet[int],
        src_pair: Optional[Tuple[int, ViewOption]],
        sink: int,
        sink_view: ViewOption,
        resource: MachineResource,
    ) -> Tuple[float, Dict[int, ViewOption]]:
        """Cost of executing `sub` (sink included, its view fixed) given the
        producer boundary `src_pair`; returns (seconds, views of sub\\{sink}).

        reference: SearchHelper::graph_cost (graph.cc:1346-1431), memoized
        by the analog of dp_state_hash (graph.cc:1531-1543)."""
        key = (
            sub,
            src_pair[0] if src_pair else -1,
            src_pair[1].key() if src_pair else 0,
            sink,
            sink_view.key(),
            self._res_key(resource),
        )
        if key in self._memo:
            self.memo_hits += 1
            return self._memo[key]

        interior = sub - {sink}
        if not interior:
            cost = self.op_cost(sink, sink_view)
            node = self.graph.nodes[sink]
            for r in node.inputs:
                if src_pair is not None and r.guid == src_pair[0]:
                    cost += self.xfer_cost(r, src_pair[1], sink_view)
            out = (cost, {})
            self._memo[key] = out
            return out

        b = self._find_bottleneck(sub, sink, src_pair)
        if b is not None:
            pre = (
                frozenset(g for g in self.graph.ancestors_of([b]) if g in sub)
                | {b}
            )
            post = sub - pre
            best: Optional[Tuple[float, Dict[int, ViewOption]]] = None
            for view in self.valid_views(b, resource):
                c1, v1 = self._graph_cost(pre, src_pair, b, view, resource)
                c2, v2 = self._graph_cost(
                    post | {sink}, (b, view), sink, sink_view, resource
                )
                c = c1 + c2
                if best is None or c < best[0]:
                    best = (c, {**v1, **v2, b: view})
            self._memo[key] = best
            return best

        out = self._nonsequence_cost(sub, src_pair, sink, sink_view, resource)
        self._memo[key] = out
        return out

    def _find_bottleneck(
        self, sub, sink, src_pair
    ) -> Optional[int]:
        """An interior node on every source→sink path within `sub`
        (reference: find_split_node via imm post-dominators,
        substitution.cc:1984)."""
        from flexflow_tpu import native

        nodes = sorted(sub)
        index = {g: i for i, g in enumerate(nodes)}
        edges = []
        for g in nodes:
            for r in self.graph.nodes[g].inputs:
                if r.guid in index:
                    edges.append((index[r.guid], index[g]))
        # virtual source feeding all sub-sources keeps ipdom rooted
        n = len(nodes)
        srcs = [
            i
            for i, g in enumerate(nodes)
            if not any(r.guid in index for r in self.graph.nodes[g].inputs)
        ]
        vs = n
        for i in srcs:
            edges.append((vs, i))
        ipdom = native.imm_post_dominators(n + 1, edges)
        if ipdom is None:
            return None
        # walk the ipdom chain from the virtual source toward the sink; the
        # first interior node on it post-dominates every source
        cur = ipdom[vs]
        while cur is not None and cur >= 0 and cur < n:
            g = nodes[cur]
            if g != sink:
                return g
            cur = ipdom[cur] if ipdom[cur] != cur else -1
        return None

    def _branches(self, sub, sink) -> List[FrozenSet[int]]:
        """Weakly-connected components of sub\\{sink}."""
        rest = set(sub) - {sink}
        comps = []
        while rest:
            seed = min(rest)  # deterministic (matches the native solver)
            comp = {seed}
            frontier = [seed]
            while frontier:
                g = frontier.pop()
                nbrs = [
                    r.guid
                    for r in self.graph.nodes[g].inputs
                    if r.guid in rest
                ]
                nbrs += [c for c in self.graph.consumers(g) if c in rest]
                for nb in nbrs:
                    if nb not in comp:
                        comp.add(nb)
                        frontier.append(nb)
            comps.append(frozenset(comp))
            rest -= comp
        return comps

    def _branch_cost(
        self, branch: FrozenSet[int], src_pair, sink, sink_view, resource
    ) -> Tuple[float, Dict[int, ViewOption]]:
        """Cost of one parallel branch: its terminal's view is enumerated,
        with the transfer onto the (already fixed) sink view charged here."""
        terms = [
            g
            for g in branch
            if not any(c in branch for c in self.graph.consumers(g))
        ]
        if len(terms) != 1:
            return self._multi_terminal_cost(
                branch, src_pair, sink, sink_view, resource
            )
        term = terms[0]
        best: Optional[Tuple[float, Dict[int, ViewOption]]] = None
        for view in self.valid_views(term, resource):
            c, v = self._graph_cost(branch, src_pair, term, view, resource)
            for r in self.graph.nodes[sink].inputs:
                if r.guid == term:
                    c += self.xfer_cost(r, view, sink_view)
            if best is None or c < best[0]:
                best = (c, {**v, term: view})
        return best

    # product cap for the exact multi-terminal solve; beyond it the greedy
    # topological pass runs instead (mirrored by native/src/unity_dp.cc)
    _MT_EXACT_CAP = 4096

    def _branch_topo_order(self, branch: FrozenSet[int]) -> List[int]:
        """Topological order within the branch, smallest guid first.
        Builder guids are already topological, but substitution rewrites
        wire fresh higher-guid producers into existing lower-guid
        consumers, so Kahn it is. Mirrored by the native solver's
        multi_terminal_cost (same smallest-first tie-break)."""
        indeg = {
            g: sum(
                1 for r in self.graph.nodes[g].inputs if r.guid in branch
            )
            for g in branch
        }
        remaining = set(branch)
        order: List[int] = []
        while remaining:
            ready = [g for g in remaining if indeg[g] == 0]
            if not ready:  # cycle (impossible in a PCG): keep guid order
                return sorted(branch)
            g = min(ready)
            order.append(g)
            remaining.remove(g)
            for c in remaining:
                indeg[c] -= sum(
                    1 for r in self.graph.nodes[c].inputs if r.guid == g
                )
        return order

    def _multi_terminal_cost(
        self, branch: FrozenSet[int], src_pair, sink, sink_view, resource
    ) -> Tuple[float, Dict[int, ViewOption]]:
        """Multi-terminal branch (no single node post-dominates it): assign
        views over the whole branch JOINTLY, charging intra-branch
        transfers, the producer boundary, and every terminal→sink transfer.
        Small branches are solved exactly (view-set product ≤ _MT_EXACT_CAP);
        larger ones greedily in topological order, each node taking the view
        minimizing its op cost plus transfers from already-assigned
        producers. Replaces the round-1 independent-minima fallback that
        charged no transfers at all and underestimated real branch costs 2×+
        (bounded by tests/test_unity_exhaustive.py)."""
        import itertools

        order = self._branch_topo_order(branch)
        pos = {g: k for k, g in enumerate(order)}
        opts = [self.valid_views(g, resource) for g in order]
        nk = len(order)

        # cost tables: per-(node, view) op costs; per-edge view-pair
        # transfer tables (producer is always earlier: order is topological)
        opc = [
            [self.op_cost(g, v) for v in cands]
            for g, cands in zip(order, opts)
        ]
        intra = []  # (ks, kd, table[src_view_idx][dst_view_idx])
        src_edges = []  # (kd, cost per dst view) from the fixed src boundary
        for kd, g in enumerate(order):
            for r in self.graph.nodes[g].inputs:
                if r.guid in pos:
                    ks = pos[r.guid]
                    intra.append(
                        (
                            ks,
                            kd,
                            [
                                [self.xfer_cost(r, vs, vd) for vd in opts[kd]]
                                for vs in opts[ks]
                            ],
                        )
                    )
                elif src_pair is not None and r.guid == src_pair[0]:
                    src_edges.append(
                        (
                            kd,
                            [
                                self.xfer_cost(r, src_pair[1], vd)
                                for vd in opts[kd]
                            ],
                        )
                    )
        sink_edges = []  # (ks, cost per src view) onto the fixed sink view
        for r in self.graph.nodes[sink].inputs:
            if r.guid in pos:
                ks = pos[r.guid]
                sink_edges.append(
                    (ks, [self.xfer_cost(r, v, sink_view) for v in opts[ks]])
                )

        def total_cost(idx) -> float:
            c = 0.0
            for k in range(nk):
                c += opc[k][idx[k]]
            for ks, kd, table in intra:
                c += table[idx[ks]][idx[kd]]
            for kd, costs in src_edges:
                c += costs[idx[kd]]
            for ks, costs in sink_edges:
                c += costs[idx[ks]]
            return c

        n_combos = 1
        for o in opts:
            n_combos *= len(o)
        if n_combos <= self._MT_EXACT_CAP:
            best = None
            for idx in itertools.product(*(range(len(o)) for o in opts)):
                c = total_cost(idx)
                if best is None or c < best[0]:
                    best = (c, idx)
            return best[0], {
                g: opts[k][best[1][k]] for k, g in enumerate(order)
            }

        idx: List[int] = []
        for k in range(nk):
            best_j = None
            for j in range(len(opts[k])):
                c = opc[k][j]
                for ks, kd, table in intra:
                    if kd == k:
                        c += table[idx[ks]][j]
                for kd, costs in src_edges:
                    if kd == k:
                        c += costs[j]
                for ks, costs in sink_edges:
                    if ks == k:
                        c += costs[j]
                if best_j is None or c < best_j[0]:
                    best_j = (c, j)
            idx.append(best_j[1])
        return total_cost(idx), {
            g: opts[k][idx[k]] for k, g in enumerate(order)
        }

    def _nonsequence_cost(
        self, sub, src_pair, sink, sink_view, resource
    ) -> Tuple[float, Dict[int, ViewOption]]:
        """No bottleneck ⇒ parallel branches. Try concurrent execution on
        vertical/horizontal resource splits and sequential on full resources
        (reference: find_optimal_nonsequence_graph_time, graph.cc:252-306)."""
        branches = self._branches(sub, sink)
        sink_cost = self.op_cost(sink, sink_view)
        if src_pair is not None:
            for r in self.graph.nodes[sink].inputs:
                if r.guid == src_pair[0]:
                    sink_cost += self.xfer_cost(r, src_pair[1], sink_view)

        # sequential: every branch gets the full resource block, times add
        seq_total = sink_cost
        seq_views: Dict[int, MachineView] = {}
        per_branch = []
        for br in branches:
            c, v = self._branch_cost(br, src_pair, sink, sink_view, resource)
            per_branch.append((br, c, v))
            seq_total += c
            seq_views.update(v)
        best = (seq_total, seq_views)

        # concurrent two-way: branches bundled into {first} vs {rest} on a
        # resource split (the reference enumerates subset splits the same
        # greedy way). Gated: the one-mesh lowering executes branches
        # sequentially, so costing sub-block concurrency would diverge
        # from the executable strategy (ctor docstring).
        if self.allow_subblock_views and len(branches) >= 2:
            first = per_branch[0][0]
            rest = [b for b, _, _ in per_branch[1:]]
            splits: List[Tuple[MachineResource, MachineResource]] = []
            for i in range(1, resource.num_nodes):
                splits.append(resource.vertical_split(i))
            for i in range(1, resource.chips_per_node):
                splits.append(resource.horizontal_split(i))
            for r1, r2 in splits:
                c1, v1 = self._branch_cost(first, src_pair, sink, sink_view, r1)
                c2 = 0.0
                v2: Dict[int, ViewOption] = {}
                for br in rest:
                    c, v = self._branch_cost(br, src_pair, sink, sink_view, r2)
                    c2 += c
                    v2.update(v)
                c = max(c1, c2) + sink_cost
                if c < best[0]:
                    best = (c, {**v1, **v2})
        return best


# -- lowering to an executable Strategy --------------------------------------


def result_to_strategy(
    result: UnityResult, graph: PCGGraph, num_devices: int, engine: str = "unity"
):
    """Reduce the per-op view map to one global mesh + TP rewrite sites
    (SURVEY §7's v1 restriction — per-op device subsets beyond one mesh are
    exported but not lowered).

    When the search's views are HETEROGENEOUS — some compute ops sharded
    on channels while others keep a wider pure-data-parallel view than the
    uniform (data = devices/tp) mesh would grant them — the lowering goes
    through `mixed_site_strategy`: full-width batch sharding outside the
    TP sites, matching what the DP search actually costed per node
    (reference: per-op MachineViews, graph.cc:1346-1431)."""
    from flexflow_tpu.parallel.strategy import (
        mixed_site_strategy,
        site_strategy,
    )
    from flexflow_tpu.search.rewrites import find_tp_sites

    channel = [v for v in result.views.values() if v.ch > 1]
    tp = Counter(v.ch for v in channel).most_common(1)[0][0] if channel else 1
    tp = max(1, min(tp, num_devices))
    while num_devices % tp != 0:
        tp -= 1

    tp_guids = {g for g, v in result.views.items() if v.ch == tp and v.ch > 1}
    sites = [
        s
        for s in find_tp_sites(graph)
        if (set(s.guids) & tp_guids) and s.divisible_by(graph, tp)
    ] if tp > 1 else []
    prefix = f"{engine}(step {result.cost * 1e3:.3f} ms)"
    uniform_dp = max(1, num_devices // tp)
    site_guids = {g for s in sites for g in s.guids}
    wants_full_dp = tp > 1 and any(
        v.ch == 1 and v.dp > uniform_dp
        for g, v in result.views.items()
        if g in graph.nodes
        and g not in site_guids
        and graph.nodes[g].op_type != OperatorType.INPUT
        and not graph.nodes[g].is_parallel_op
    )
    if wants_full_dp:
        return mixed_site_strategy(
            graph, num_devices, tp, sites, name_prefix=prefix
        )
    return site_strategy(
        graph, num_devices, tp, sites, name_prefix=prefix
    )


def save_views(
    result: UnityResult, graph: PCGGraph, path: str, engine: str = "unity"
):
    """Per-op view export (reference: save_strategies_to_file,
    strategy.cc:156 — per-op ParallelConfig maps)."""
    import json

    doc = {
        "version": 1,
        "engine": engine,
        "simulated_step_ms": result.cost * 1e3,
        "ops": {
            graph.nodes[g].name: {
                "start_device_id": v.view.start_device_id,
                "dims": list(v.view.dims),
                "strides": list(v.view.strides),
                "dp": v.dp,
                "ch": v.ch,
            }
            for g, v in sorted(result.views.items())
            if g in graph.nodes
        },
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
