"""Automatic parallelization search.

TPU rebuild of the reference's two search engines (SURVEY §2.5):

  * the Unity substitution search (reference: GraphSearchHelper::
    graph_optimize, src/runtime/substitution.cc:1884-2194 — priority-queue
    rewrite search ranked by simulated cost) becomes a **mesh × rewrite-site
    search**: enumerate (dp, tp) factorizations of the chip count, detect TP
    rewrite sites (rewrites.find_tp_sites), greedily toggle sites by
    simulated step time, then spend the remaining `--budget` on MCMC
    perturbations (reference: FFModel::mcmc_optimize, model.cc:3271-3342 —
    random flip, accept with exp(-alpha·Δ)).
  * per-candidate cost comes from search.simulator (the reference's
    Simulator::simulate_runtime role).

The v1 restriction documented in SURVEY §7 applies: every strategy lives on
ONE global mesh (data × model axes); per-op device subsets
(start_device_id/strides MachineViews) are not searched.
"""

from __future__ import annotations

import math
import random
from typing import List, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.parallel.strategy import Strategy, data_parallel_strategy
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.rewrites import Site, find_tp_sites
from flexflow_tpu.search.simulator import GraphCost, estimate_graph_cost

_MODEL_AXIS = 1  # mesh axis index for tensor parallelism ("model")


def _annotate_data_parallel(graph: PCGGraph, dp: int):
    """Shard every input's batch dim exactly dp ways; the mesh data axis is
    dp wide, so a batch dp does not divide makes the candidate infeasible."""
    from flexflow_tpu.parallel.strategy import annotate_input_batch

    annotate_input_batch(graph, dp, strict=True)


def _candidate_graph(
    base: PCGGraph, dp: int, tp: int, sites: Sequence[Site], on: Sequence[bool]
) -> Optional[PCGGraph]:
    from flexflow_tpu.runtime.executor import propagate_shapes

    g = base.copy()
    try:
        _annotate_data_parallel(g, dp)
        for site, enabled in zip(sites, on):
            if enabled:
                site.apply(g, tp, _MODEL_AXIS)
        propagate_shapes(g)
    except (ValueError, KeyError):
        return None
    return g


def _mesh_factorizations(num_devices: int) -> List[Tuple[int, int]]:
    """(dp, tp) pairs with dp*tp == num_devices (reference enumerates
    divisor-sized machine views, graph.cc:1783-1814)."""
    out = []
    for tp in range(1, num_devices + 1):
        if num_devices % tp == 0:
            out.append((num_devices // tp, tp))
    return out


class SearchResult:
    def __init__(self, dp, tp, sites, on, cost: GraphCost):
        self.dp = dp
        self.tp = tp
        self.sites = list(sites)
        self.on = list(on)
        self.cost = cost

    def describe(self) -> str:
        n_on = sum(self.on)
        return (
            f"mesh(data={self.dp}, model={self.tp}), {n_on}/{len(self.on)} "
            f"TP sites, simulated step {self.cost.step_time * 1e3:.3f} ms"
        )


def optimize(
    graph: PCGGraph,
    num_devices: int,
    spec: MachineSpec,
    budget: int = 10,
    alpha: float = 1.05,
    measure: bool = False,
    seed: int = 0,
    verbose: bool = False,
    machine_model=None,
    mixed_precision: bool = False,
) -> SearchResult:
    """Run the search on a PCG; returns the best found configuration."""
    cm = CostModel(
        spec,
        measure=measure,
        machine_model=machine_model,
        mixed_precision=mixed_precision,
    )
    rng = random.Random(seed)
    evals = 0
    best: Optional[SearchResult] = None

    def evaluate(dp, tp, sites, on) -> Optional[GraphCost]:
        nonlocal evals
        evals += 1
        g = _candidate_graph(graph, dp, tp, sites, on)
        if g is None:
            return None
        mesh_sizes = (dp, tp) if tp > 1 else (dp,)
        cost = estimate_graph_cost(g, cm, mesh_sizes)
        if not cost.feasible(spec):
            return None
        return cost

    for dp, tp in _mesh_factorizations(num_devices):
        sites = [
            s for s in find_tp_sites(graph) if tp == 1 or s.divisible_by(graph, tp)
        ]
        if tp > 1 and not sites:
            continue
        on = [False] * len(sites)
        cost = evaluate(dp, tp, sites, on)
        if cost is None:
            continue
        cur = SearchResult(dp, tp, sites, on, cost)
        if tp > 1:
            # greedy forward pass over sites in graph order
            for i in range(len(sites)):
                trial = list(cur.on)
                trial[i] = True
                c = evaluate(dp, tp, sites, trial)
                if c is not None and c.step_time < cur.cost.step_time:
                    cur = SearchResult(dp, tp, sites, trial, c)
        if verbose:
            print(f"[search] {cur.describe()}")
        if best is None or cur.cost.step_time < best.cost.step_time:
            best = cur

    if best is None:
        raise RuntimeError("search found no feasible strategy")

    # MCMC refinement with the remaining budget (reference: mcmc_optimize)
    cur = best
    while evals < budget and cur.sites:
        i = rng.randrange(len(cur.sites))
        trial = list(cur.on)
        trial[i] = not trial[i]
        c = evaluate(cur.dp, cur.tp, cur.sites, trial)
        if c is None:
            continue
        delta = c.step_time - cur.cost.step_time
        scale = max(cur.cost.step_time, 1e-9)
        if delta < 0 or rng.random() < math.exp(-alpha * delta / scale):
            cur = SearchResult(cur.dp, cur.tp, cur.sites, trial, c)
        if cur.cost.step_time < best.cost.step_time:
            best = cur

    return best


def result_to_strategy(result: SearchResult, graph: PCGGraph) -> Strategy:
    """Lower via the shared searched-strategy builder; the search already
    validated dp feasibility through _candidate_graph, so site_strategy's
    effective-dp clamp resolves to result.dp."""
    from flexflow_tpu.parallel.strategy import site_strategy

    sites = [s for s, enabled in zip(result.sites, result.on) if enabled]
    return site_strategy(
        graph,
        result.dp * result.tp,
        result.tp,
        sites,
        name_prefix=f"searched({result.cost.step_time * 1e3:.3f} ms)",
    )


def search_strategy(model, num_devices: int) -> Strategy:
    """compile()-time entry (reference: graph_optimize_task,
    graph.cc:1545-1613)."""
    cfg = model.config
    # search-without-hardware overrides (reference: model.cc:3673-3680)
    n = num_devices
    if cfg.search_num_workers > 0:
        n = cfg.search_num_workers * max(1, cfg.search_num_nodes)
    spec = MachineSpec(
        num_nodes=max(1, cfg.search_num_nodes)
        if cfg.search_num_nodes > 0
        else max(1, cfg.num_nodes),
        chips_per_node=max(1, n // max(1, cfg.num_nodes)),
        chip=cfg.chip,
    )
    if n <= 1:
        return data_parallel_strategy(num_devices, model.graph)

    if cfg.search_engine not in ("mesh", "unity", "mcmc"):
        raise ValueError(
            f"unknown --search-engine {cfg.search_engine!r}; "
            "expected mesh | unity | mcmc"
        )
    from flexflow_tpu.search.machine_model import build_machine_model

    mm = build_machine_model(cfg, spec)
    if cfg.search_engine in ("unity", "mcmc"):
        from flexflow_tpu.search import unity as unity_mod

        if cfg.search_engine == "unity":
            result = unity_mod.UnitySearch(
                model.graph,
                spec,
                machine_model=mm,
                mixed_precision=cfg.allow_mixed_precision,
            ).optimize()
        else:
            from flexflow_tpu.search.mcmc import mcmc_optimize

            result = mcmc_optimize(
                model.graph,
                spec,
                budget=max(cfg.search_budget, 1),
                alpha=cfg.search_alpha,
                seed=cfg.seed,
                verbose=cfg.profiling,
                machine_model=mm,
                mixed_precision=cfg.allow_mixed_precision,
            )
        # reference prints exactly this at the end of its search
        # (substitution.cc:1909, model.cc:3298)
        print(f"Optimal cost: {result.cost * 1e3:.6f}")
        if cfg.export_strategy_file:
            unity_mod.save_views(
                result,
                model.graph,
                cfg.export_strategy_file,
                engine=cfg.search_engine,
            )
        return unity_mod.result_to_strategy(
            result, model.graph, num_devices, engine=cfg.search_engine
        )

    result = optimize(
        model.graph,
        n,
        spec,
        budget=max(cfg.search_budget, 1),
        alpha=cfg.search_alpha,
        seed=cfg.seed,
        verbose=cfg.profiling,
        machine_model=mm,
        mixed_precision=cfg.allow_mixed_precision,
    )
    print(f"[flexflow_tpu] search: best strategy = {result.describe()}")
    if cfg.export_strategy_file:
        from flexflow_tpu.search.strategy_io import save_search_result

        save_search_result(result, model.graph, cfg.export_strategy_file)
    return result_to_strategy(result, model.graph)
