"""Automatic parallelization search.

TPU rebuild of the reference's two search engines (SURVEY §2.5):

  * the Unity substitution search (reference: GraphSearchHelper::
    graph_optimize, src/runtime/substitution.cc:1884-2194 — priority-queue
    rewrite search ranked by simulated cost) becomes a **mesh × rewrite-site
    search**: enumerate (dp, tp) factorizations of the chip count, detect TP
    rewrite sites (rewrites.find_tp_sites), greedily toggle sites by
    simulated step time, then spend the remaining `--budget` on MCMC
    perturbations (reference: FFModel::mcmc_optimize, model.cc:3271-3342 —
    random flip, accept with exp(-alpha·Δ)).
  * per-candidate cost comes from search.simulator (the reference's
    Simulator::simulate_runtime role).

The v1 restriction documented in SURVEY §7 applies: every strategy lives on
ONE global mesh (data × model axes); per-op device subsets
(start_device_id/strides MachineViews) are not searched.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.parallel.strategy import Strategy, data_parallel_strategy
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.rewrites import Site, find_tp_sites
from flexflow_tpu.search.simulator import (
    GraphCost,
    _sparse_embedding_rows,
    estimate_graph_cost,
    sparse_embedding_node_cost,
)

_MODEL_AXIS = 1  # mesh axis index for tensor parallelism ("model")


def _annotate_data_parallel(graph: PCGGraph, dp: int):
    """Shard every input's batch dim exactly dp ways; the mesh data axis is
    dp wide, so a batch dp does not divide makes the candidate infeasible."""
    from flexflow_tpu.parallel.strategy import annotate_input_batch

    annotate_input_batch(graph, dp, strict=True)


def _candidate_graph(
    base: PCGGraph, dp: int, tp: int, sites: Sequence[Site], on: Sequence[bool]
) -> Optional[PCGGraph]:
    from flexflow_tpu.runtime.executor import propagate_shapes

    g = base.copy()
    try:
        _annotate_data_parallel(g, dp)
        for site, enabled in zip(sites, on):
            if enabled:
                site.apply(g, tp, _MODEL_AXIS)
        # partition-move peephole (create_partition_*_combine analogs):
        # must run here AND in the strategy lowering (site_strategy) so
        # the costed candidate is the executed graph
        from flexflow_tpu.search.peephole import sink_combines

        sink_combines(g)
        propagate_shapes(g)
    except (ValueError, KeyError):
        return None
    return g


def _mesh_factorizations(num_devices: int) -> List[Tuple[int, int]]:
    """(dp, tp) pairs with dp*tp == num_devices (reference enumerates
    divisor-sized machine views, graph.cc:1783-1814)."""
    out = []
    for tp in range(1, num_devices + 1):
        if num_devices % tp == 0:
            out.append((num_devices // tp, tp))
    return out


def _second_axis_candidate(
    base: PCGGraph, strategy, dp: int, deg: int, cm: CostModel, spec
) -> Optional[GraphCost]:
    """Cost a (dp, <axis>) mesh strategy (seq or spatial): the second
    axis must actually shard some input dim, else this is pure dp on a
    bigger mesh (idle chips) — never profitable, skip."""
    from flexflow_tpu.runtime.executor import propagate_shapes

    g = base.copy()
    try:
        strategy.apply(g)
        propagate_shapes(g)
    except (ValueError, KeyError):
        return None
    sharded = any(
        d.degree == deg and d.parallel_idx == 1
        for n in g.nodes.values()
        if n.op_type == OperatorType.INPUT
        for d in n.output_shapes[0].dims
    )
    if not sharded:
        return None
    cost = estimate_graph_cost(g, cm, (dp, deg))
    return cost if cost.feasible(spec) else None


def _seq_candidate(
    base: PCGGraph, dp: int, sp: int, cm: CostModel, spec,
    seq_mode: str = "ring",
) -> Optional[GraphCost]:
    """Cost a (dp, sp) sequence-parallel mesh: inputs' seq dim sharded on
    axis 1; attention pays the ring-exchange or Ulysses all-to-all term
    per seq_mode (CostModel.op_cost reads the node's seq_parallel)."""
    from flexflow_tpu.parallel.strategy import sequence_parallel_strategy

    return _second_axis_candidate(
        base,
        sequence_parallel_strategy(dp, sp, seq_mode=seq_mode),
        dp,
        sp,
        cm,
        spec,
    )


def _spatial_candidate(
    base: PCGGraph, dp: int, hp: int, cm: CostModel, spec
) -> Optional[GraphCost]:
    """Cost a (dp, spatial) mesh: image inputs' H dim sharded on axis 1;
    convs ride GSPMD's windowed-op halo exchange (reference:
    --enable-attribute-parallel, model.cc:3602 — partition non-sample
    activation dims)."""
    from flexflow_tpu.parallel.strategy import spatial_parallel_strategy

    return _second_axis_candidate(
        base, spatial_parallel_strategy(dp, hp), dp, hp, cm, spec
    )


def _pipeline_candidate(
    base: PCGGraph, structure, dp: int, pp: int, mb: int, cm: CostModel,
    spec: MachineSpec = None,
) -> Optional[GraphCost]:
    """Analytic GPipe cost of a (dp, pipe) mesh: per-stage compute is the
    trunk's dp-sharded cost / pp, schedule stretch is the GPipe bubble
    (m + pp - 1)/m (parallel/pipeline.pipeline_bubble_fraction), plus
    boundary ppermute hops and the dp gradient all-reduce."""
    from flexflow_tpu.runtime.executor import propagate_shapes

    if structure.num_blocks % pp != 0:
        return None
    # the executor rejects trunks with host/aux hooks (cache memoizer,
    # MoE balance loss — PipelinedExecutor.__init__); don't propose
    # candidates guaranteed to fail compile
    for blk in structure.blocks:
        for gg in blk:
            n = base.nodes[gg]
            if n.op_type == OperatorType.CACHE:
                return None
            if n.op_type in (
                OperatorType.AGGREGATE,
                OperatorType.AGGREGATE_SPEC,
            ) and float(n.params.get("lambda_bal", 0.0)) > 0.0:
                return None
    g = base.copy()
    try:
        _annotate_data_parallel(g, dp)
        propagate_shapes(g)
    except (ValueError, KeyError):
        return None
    block_guids = {gg for blk in structure.blocks for gg in blk}
    trunk = 0.0
    trunk_fwd = 0.0
    rest = 0.0
    sync = 0.0
    update = 0.0
    trunk_weight_bytes = 0.0
    rest_weight_bytes = 0.0
    act_bytes = 0.0
    trunk_act_bytes = 0.0
    for guid, node in g.nodes.items():
        if node.op_type == OperatorType.INPUT or node.is_parallel_op:
            continue
        in_shapes = [g.shape_of(r) for r in node.inputs]
        c = sparse_embedding_node_cost(g, guid, node, cm)
        sparse_table = c is not None
        if c is None:
            c = cm.op_cost(node, in_shapes)
        t = c.forward_time + c.backward_time
        out_bytes = sum(s.piece_bytes() for s in node.output_shapes)
        act_bytes += out_bytes
        if guid in block_guids:
            trunk += t
            trunk_fwd += c.forward_time
            trunk_act_bytes += out_bytes
        else:
            rest += t
        sp_rows = _sparse_embedding_rows(g, guid) if sparse_table else None
        for w in node.weight_shapes:
            # grads only need reducing over the dp replicas that
            # computed them
            if guid in block_guids:
                trunk_weight_bytes += w.piece_bytes()
            else:
                rest_weight_bytes += w.piece_bytes()
            if sparse_table:
                # no table-sized gradient ever materializes: no grad
                # all-reduce, touched-rows update only (same basis as
                # estimate_graph_cost's weight loop) + the touched-row
                # all-gather over the dp replicas (sparse_sync_cost)
                update += cm.sparse_update_cost(w, sp_rows)
                if dp > 1:
                    sync += cm.sparse_sync_cost(
                        sp_rows * w.dims[-1].piece_size * w.dtype.size_bytes,
                        dp,
                    )
                continue
            if dp > 1:
                sync += cm.all_reduce(cm.piece_bytes(w), dp)
            update += cm.update_cost(w)
    stage = trunk / pp
    stretch = (mb + pp - 1) / mb
    exit_shape = g.shape_of(TensorRef(structure.blocks[-1][-1], 0))
    boundary_bytes = exit_shape.piece_volume() * cm.elem_bytes(exit_shape)
    hop_bytes = boundary_bytes / mb
    hops = 2.0 * (mb + pp - 2) * cm._ici_time(hop_bytes) if pp > 1 else 0.0
    # compute and hop transfers overlap in the schedule (a stage sends
    # microbatch i while computing i+1): the trunk is bounded by whichever
    # resource saturates, not their sum
    trunk_time = max(stage * stretch, hops)
    # trunk weights (+grads+opt state, the 3.0) are STACKED and sharded
    # over the pipe axis (runtime/pipeline_executor.py storage), so each
    # chip holds 1/pp of them; prologue/epilogue weights replicate.
    weight_mem = rest_weight_bytes * 3.0 + trunk_weight_bytes * 3.0 / pp
    # activation residuals: gpipe stores each block's internals; 1f1b
    # remats block bodies, keeping only stage-boundary activations per
    # in-flight microbatch (PipelineSpec.schedule)
    mem_gpipe = int(weight_mem + act_bytes / pp)
    mem_1f1b = int(
        weight_mem
        + (act_bytes - trunk_act_bytes) / pp
        + boundary_bytes * (structure.num_blocks / pp)
    )
    schedule = "gpipe"
    memory = mem_gpipe
    if spec is not None:
        probe = GraphCost(0, 0, 0, 0, 0, memory_per_chip=mem_gpipe)
        if not probe.feasible(spec) and mem_1f1b < mem_gpipe:
            schedule = "1f1b"
            memory = mem_1f1b
            # remat recomputes each block's forward during the backward
            # (jax.checkpoint in pipeline_executor._block_fn) — the
            # memory saving is not free
            trunk_time = max((trunk + trunk_fwd) / pp * stretch, hops)
    # one program launch per step, same basis as estimate_graph_cost's
    # step_floor — without it pipeline candidates would carry a
    # one-floor advantage over every simulator-priced candidate
    step_floor = cm.dispatch_floor() if cm.measure else 0.0
    cost = GraphCost(
        step_time=rest + trunk_time + sync + update + step_floor,
        compute_time=rest + trunk,
        comm_time=hops,
        sync_time=sync,
        update_time=update,
        memory_per_chip=memory,
    )
    if spec is not None and not cost.feasible(spec):
        return None
    cost.schedule = schedule
    return cost


def _mixed_candidate(
    base: PCGGraph, num_devices: int, tp: int, sites, cm: CostModel, spec
) -> Optional[GraphCost]:
    """Cost the heterogeneous lowering (parallel.strategy.
    mixed_site_strategy): TP sites on the model axis, everything else
    FULL-width data-parallel — the reference's per-op MachineView pattern
    (graph.cc:1346-1431, e.g. DLRM sharded tables + dp MLPs)."""
    from flexflow_tpu.parallel.strategy import mixed_site_strategy
    from flexflow_tpu.runtime.executor import propagate_shapes

    strategy = mixed_site_strategy(base, num_devices, tp, sites)
    if "mixed" not in strategy.name:
        return None  # fell back to the uniform lowering: already covered
    g = base.copy()
    try:
        strategy.apply(g)
        propagate_shapes(g)
    except (ValueError, KeyError):
        return None
    cost = estimate_graph_cost(g, cm, strategy.mesh_config.axis_sizes)
    return cost if cost.feasible(spec) else None


class SearchResult:
    """One searched configuration. kind ∈ {"tp", "seq", "pipeline",
    "mixed", "spatial"}: which parallel axis family the second mesh axis carries
    (VERDICT r1 item 2 — the search explores pp/sp/ep, not just dp×tp;
    ep rides the "tp" kind through ExpertParallelSite on the model axis;
    "mixed" is the heterogeneous per-op lowering, VERDICT r1 item 8)."""

    def __init__(self, dp, tp, sites, on, cost: GraphCost, kind="tp",
                 extra=None):
        self.dp = dp
        self.tp = tp
        self.sites = list(sites)
        self.on = list(on)
        self.cost = cost
        self.kind = kind
        self.extra = dict(extra or {})

    def describe(self) -> str:
        if self.kind == "mixed":
            return (
                f"mixed mesh(data={self.dp}, model={self.tp}), "
                f"{len(self.sites)} TP sites + full-width dp, simulated "
                f"step {self.cost.step_time * 1e3:.3f} ms"
            )
        if self.kind == "seq":
            mode = self.extra.get("seq_mode", "ring")
            return (
                f"mesh(data={self.dp}, seq={self.extra['sp']}), {mode} "
                f"attention, simulated step {self.cost.step_time * 1e3:.3f} ms"
            )
        if self.kind == "spatial":
            return (
                f"mesh(data={self.dp}, spatial={self.extra['hp']}), "
                f"simulated step {self.cost.step_time * 1e3:.3f} ms"
            )
        if self.kind == "pipeline":
            sched = self.extra.get("schedule", "gpipe")
            return (
                f"mesh(data={self.dp}, pipe={self.extra['pp']}), "
                f"{self.extra['num_blocks']} blocks, "
                f"{self.extra['mb']} microbatches ({sched}), simulated "
                f"step {self.cost.step_time * 1e3:.3f} ms"
            )
        n_on = sum(self.on)
        return (
            f"mesh(data={self.dp}, model={self.tp}), {n_on}/{len(self.on)} "
            f"TP sites, simulated step {self.cost.step_time * 1e3:.3f} ms"
        )


def extra_axis_candidates(
    graph: PCGGraph,
    num_devices: int,
    cm: CostModel,
    spec: MachineSpec,
    attribute_parallel: bool = False,
    verbose: bool = False,
    trace=None,
):
    """The strategy families BEYOND the dp×tp grid — mixed (heterogeneous
    per-op), sequence (ring/Ulysses), spatial, pipeline. Shared by the
    mesh engine's optimize() and by the unity/mcmc entries, so every
    engine covers the whole space its runtime can execute (the reference
    has ONE search over everything its runtime does,
    substitution.cc:1721-1862). Returns (results, evals). `trace`
    (telemetry.SearchTrace) records each feasible candidate with its
    GraphCost breakdown."""
    results = []
    evals = 0

    def _rec(cur: "SearchResult") -> None:
        if trace is None:
            return
        c = cur.cost
        descr = cur.describe()  # fresh string — rows hold no live state
        trace.candidate(
            "extra_axis",
            name=descr,
            dp=cur.dp,
            step_time=c.step_time,
            compute_time=c.compute_time,
            comm_time=c.comm_time,
            sync_time=c.sync_time,
            update_time=c.update_time,
            memory_per_chip=float(c.memory_per_chip),
            feasible=bool(c.feasible(spec)),
        )

    # heterogeneous candidates: TP sites on the model axis, everything
    # else full-width data-parallel (reference: per-op MachineViews,
    # graph.cc:1346-1431 — the DLRM sharded-tables + dp-MLPs pattern)
    for _dp, tp in _mesh_factorizations(num_devices):
        if tp == 1:
            continue
        all_sites = [
            s for s in find_tp_sites(graph) if s.divisible_by(graph, tp)
        ]
        if not all_sites:
            continue
        # try sharding just the weight-heaviest site class (embeddings
        # first — the canonical mixed pattern) and the full site set
        from flexflow_tpu.search.rewrites import EmbeddingSite

        emb_sites = [s for s in all_sites if isinstance(s, EmbeddingSite)]
        for sites in ([emb_sites] if emb_sites else []) + [all_sites]:
            evals += 1
            cost = _mixed_candidate(graph, num_devices, tp, sites, cm, spec)
            if cost is None:
                continue
            cur = SearchResult(
                num_devices // tp, tp, sites, [True] * len(sites), cost,
                kind="mixed",
            )
            if verbose:
                print(f"[search] {cur.describe()}")
            _rec(cur)
            results.append(cur)

    # sequence-parallel candidates: (dp, sp) meshes with ring attention
    # (beyond-reference axis; the reference's seq dim is shardable but no
    # substitution ever exploits it, SURVEY §2.4)
    from flexflow_tpu.parallel.strategy import ulysses_eligible

    for dp, sp in _mesh_factorizations(num_devices):
        if sp == 1:
            continue
        modes = ["ring"]
        if any(ulysses_eligible(n, sp) for n in graph.nodes.values()):
            modes.append("ulysses")
        for seq_mode in modes:
            evals += 1
            cost = _seq_candidate(graph, dp, sp, cm, spec, seq_mode=seq_mode)
            if cost is None:
                continue
            cur = SearchResult(
                dp, 1, [], [], cost, kind="seq",
                extra={"sp": sp, "seq_mode": seq_mode},
            )
            if verbose:
                print(f"[search] {cur.describe()}")
            _rec(cur)
            results.append(cur)

    # attribute/spatial candidates: image H over the second axis
    # (reference: --enable-attribute-parallel opt-in, model.cc:3602)
    if attribute_parallel:
        for dp, hp in _mesh_factorizations(num_devices):
            if hp == 1:
                continue
            evals += 1
            cost = _spatial_candidate(graph, dp, hp, cm, spec)
            if cost is None:
                continue
            cur = SearchResult(
                dp, 1, [], [], cost, kind="spatial", extra={"hp": hp}
            )
            if verbose:
                print(f"[search] {cur.describe()}")
            _rec(cur)
            results.append(cur)

    # pipeline candidates: (dp, pipe) meshes over a repeated-block trunk
    # (reference declares OP_PIPELINE only, ffconst.h:151)
    from flexflow_tpu.search.blocks import find_block_structure

    structure = find_block_structure(graph)
    if structure is not None:
        for dp, pp in _mesh_factorizations(num_devices):
            if pp == 1:
                continue
            for mb in (4, 8):
                evals += 1
                cost = _pipeline_candidate(
                    graph, structure, dp, pp, mb, cm, spec
                )
                if cost is None:
                    continue
                cur = SearchResult(
                    dp, 1, [], [], cost, kind="pipeline",
                    extra={
                        "pp": pp,
                        "mb": mb,
                        "num_blocks": structure.num_blocks,
                        "schedule": getattr(cost, "schedule", "gpipe"),
                    },
                )
                if verbose:
                    print(f"[search] {cur.describe()}")
                _rec(cur)
                results.append(cur)

    return results, evals


def optimize(
    graph: PCGGraph,
    num_devices: int,
    spec: MachineSpec,
    budget: int = 10,
    alpha: float = 1.05,
    measure: bool = False,
    seed: int = 0,
    verbose: bool = False,
    machine_model=None,
    mixed_precision: bool = False,
    calibration_file: str = "",
    attribute_parallel: bool = False,
    sparse_embedding: bool = True,
    _explore_fuse: bool = True,
    trace=None,
) -> SearchResult:
    """Run the search on a PCG; returns the best found configuration.

    _explore_fuse: also search the activation-fused variant of the graph
    (peephole.fuse_linear_activation — create_linear_relu_merge analog)
    and keep whichever graph's best strategy wins; the winning result
    carries extra={"fuse": True} so the lowering fuses before applying
    sites (whose guids were found on the fused graph).

    trace: an optional telemetry.SearchTrace — every candidate the
    mesh × rewrite-site search scores lands in it with its full
    GraphCost breakdown (via estimate_graph_cost's trace hook)."""
    cm = CostModel(
        spec,
        measure=measure,
        machine_model=machine_model,
        mixed_precision=mixed_precision,
        calibration_file=calibration_file,
        sparse_embedding=sparse_embedding,
    )
    rng = random.Random(seed)
    evals = 0
    best: Optional[SearchResult] = None

    def evaluate(dp, tp, sites, on) -> Optional[GraphCost]:
        nonlocal evals
        evals += 1
        g = _candidate_graph(graph, dp, tp, sites, on)
        if g is None:
            return None
        mesh_sizes = (dp, tp) if tp > 1 else (dp,)
        cost = estimate_graph_cost(
            g, cm, mesh_sizes, trace=trace,
            trace_label=f"mesh(dp={dp},tp={tp},sites_on={sum(on)})",
        )
        if not cost.feasible(spec):
            return None
        return cost

    # dp-only candidates that deliberately leave chips idle (a dp smaller
    # than the chip count): with a tiny batch the full mesh may be
    # unusable, and an idle-chip dp baseline must still beat a forced
    # full-mesh candidate (the reference searches device SUBSETS via
    # MachineResource splits, graph.cc:252-306)
    idle_dps = [
        (d, 1)
        for d in range(1, num_devices)
        if num_devices % d == 0
    ]
    for dp, tp in idle_dps + _mesh_factorizations(num_devices):
        sites = [
            s for s in find_tp_sites(graph) if tp == 1 or s.divisible_by(graph, tp)
        ]
        if tp > 1 and not sites:
            continue
        on = [False] * len(sites)
        cost = evaluate(dp, tp, sites, on)
        if cost is None:
            continue
        cur = SearchResult(dp, tp, sites, on, cost)
        if tp > 1:
            # greedy forward pass over sites in graph order
            for i in range(len(sites)):
                trial = list(cur.on)
                trial[i] = True
                c = evaluate(dp, tp, sites, trial)
                if c is not None and c.step_time < cur.cost.step_time:
                    cur = SearchResult(dp, tp, sites, trial, c)
        if verbose:
            print(f"[search] {cur.describe()}")
        if best is None or cur.cost.step_time < best.cost.step_time:
            best = cur

    extra_results, extra_evals = extra_axis_candidates(
        graph, num_devices, cm, spec,
        attribute_parallel=attribute_parallel, verbose=verbose,
    )
    evals += extra_evals
    for cur in extra_results:
        if best is None or cur.cost.step_time < best.cost.step_time:
            best = cur

    if best is None:
        raise RuntimeError("search found no feasible strategy")

    # MCMC refinement with the remaining budget (reference: mcmc_optimize)
    cur = best
    while evals < budget and cur.kind == "tp" and cur.sites:
        i = rng.randrange(len(cur.sites))
        trial = list(cur.on)
        trial[i] = not trial[i]
        c = evaluate(cur.dp, cur.tp, cur.sites, trial)
        if c is None:
            continue
        delta = c.step_time - cur.cost.step_time
        scale = max(cur.cost.step_time, 1e-9)
        if delta < 0 or rng.random() < math.exp(-alpha * delta / scale):
            cur = SearchResult(cur.dp, cur.tp, cur.sites, trial, c)
        if cur.cost.step_time < best.cost.step_time:
            best = cur

    # the fuse rewrite as a searched graph variant (reference: the
    # create_linear_relu_merge xfer competes inside base_optimize)
    if _explore_fuse:
        from flexflow_tpu.search.peephole import fuse_linear_activation

        fused = graph.copy()
        if fuse_linear_activation(fused):
            fbest = optimize(
                fused, num_devices, spec, budget=budget, alpha=alpha,
                measure=measure, seed=seed, verbose=verbose,
                machine_model=machine_model,
                mixed_precision=mixed_precision,
                calibration_file=calibration_file,
                attribute_parallel=attribute_parallel,
                sparse_embedding=sparse_embedding,
                _explore_fuse=False,
                trace=trace,
            )
            if fbest.cost.step_time < best.cost.step_time:
                fbest.extra["fuse"] = True
                best = fbest

    return best


def result_to_strategy(result: SearchResult, graph: PCGGraph) -> Strategy:
    """Lower via the shared searched-strategy builders; the search already
    validated dp feasibility through _candidate_graph, so site_strategy's
    effective-dp clamp resolves to result.dp."""
    from flexflow_tpu.parallel.strategy import (
        pipeline_strategy,
        sequence_parallel_strategy,
        site_strategy,
    )

    if result.extra.get("fuse"):
        # the winning strategy was found on the activation-fused graph:
        # fuse first (guid-stable), then lower the rest of the result
        from flexflow_tpu.search.peephole import fuse_linear_activation

        inner = result_to_strategy(
            SearchResult(
                result.dp, result.tp, result.sites, result.on,
                result.cost, result.kind,
                {k: v for k, v in result.extra.items() if k != "fuse"},
            ),
            graph,
        )
        orig_apply = inner._apply

        def apply(g):
            fuse_linear_activation(g)
            if orig_apply is not None:
                orig_apply(g)

        inner._apply = apply
        inner.name = f"{inner.name} + fused activations"
        return inner

    prefix = f"searched({result.cost.step_time * 1e3:.3f} ms)"
    if result.kind == "mixed":
        from flexflow_tpu.parallel.strategy import mixed_site_strategy

        return mixed_site_strategy(
            graph,
            result.dp * result.tp,
            result.tp,
            result.sites,
            name_prefix=prefix,
        )
    if result.kind == "seq":
        s = sequence_parallel_strategy(
            result.dp,
            result.extra["sp"],
            graph,
            seq_mode=result.extra.get("seq_mode", "ring"),
        )
        s.name = f"{prefix}: {s.name}"
        return s
    if result.kind == "spatial":
        from flexflow_tpu.parallel.strategy import spatial_parallel_strategy

        s = spatial_parallel_strategy(result.dp, result.extra["hp"], graph)
        s.name = f"{prefix}: {s.name}"
        return s
    if result.kind == "pipeline":
        return pipeline_strategy(
            graph,
            result.dp,
            result.extra["pp"],
            num_microbatches=result.extra["mb"],
            schedule=result.extra.get("schedule", "gpipe"),
            name_prefix=prefix,
        )
    sites = [s for s, enabled in zip(result.sites, result.on) if enabled]
    return site_strategy(
        graph,
        result.dp * result.tp,
        result.tp,
        sites,
        name_prefix=prefix,
    )


# -- serving (decode-regime) search -----------------------------------------
#
# The training search above minimizes one TRAIN step; a serving deployment
# minimizes the per-token decode latency of flexflow_tpu.serving's engine,
# which lives in the weight-bandwidth-bound regime CostModel.decode_op_cost
# prices. The two regimes pick different strategies on the same model and
# machine: at decode batch 1 a dp mesh leaves every chip but one idle while
# TP over heads divides the dominant weight-read term, so TP wins — the
# inverse of the training verdict, where dp's gradient all-reduce is cheap
# next to the compute it parallelizes.

# ops whose weights a serving candidate shards on the model axis, with the
# divisibility rule the candidate must satisfy
_DECODE_TP_OPS = {
    OperatorType.LINEAR: lambda n: int(n.params["out_features"]),
    OperatorType.MULTIHEAD_ATTENTION: lambda n: int(n.params["num_heads"]),
    OperatorType.EMBEDDING: lambda n: int(n.params["out_dim"]),
}

#: modeled host round-trip per decode dispatch/reconcile (device-resident
#: multi-step decode amortizes this over K fused steps): dispatch
#: enqueue + output materialization + scheduler bookkeeping — the tax
#: BENCH_ASYNC measured dominating small-batch decode on the host side
DECODE_HOST_SYNC_S = 50e-6


class ServingSearchResult:
    """One costed serving configuration (mesh + per-token step time).

    `max_in_flight` (filled when the caller supplies a prompt/generation
    length distribution) is the capacity estimate: how many concurrent
    sequences of that profile the per-chip cache byte budget holds under
    the priced KV layout — the number the paged cache exists to raise.
    It prices each sequence at its steady-state footprint, i.e. the
    capacity OPTIMISTIC admission reaches; `max_in_flight_reserve` is
    the same budget divided by the worst case the preemption-free
    reserve gate charges (prompt + full max_new_tokens budget), so the
    gap between the two numbers is exactly what switching
    `--admission optimistic` buys — at the price of occasional
    preemption-by-recompute (estimate_recompute_step)."""

    def __init__(
        self,
        dp: int,
        tp: int,
        batch: int,
        kv_len: int,
        cost,
        page_size: int = 0,
        max_in_flight: Optional[int] = None,
        max_in_flight_reserve: Optional[int] = None,
        fused_steps: int = 1,
    ):
        self.dp = dp
        self.tp = tp
        self.batch = batch
        self.kv_len = kv_len
        self.cost = cost
        self.page_size = page_size
        self.max_in_flight = max_in_flight
        self.max_in_flight_reserve = max_in_flight_reserve
        # device-resident multi-step decode: the window depth K that
        # minimized amortized per-token time (1 = step-at-a-time)
        self.fused_steps = int(fused_steps)
        # Which mesh the engine will ACTUALLY execute. The search alone
        # does not apply anything — serving inherits the training
        # strategy's sharding unless `FFModel.compile_for_serving` flips
        # this to "applied" after placing weights and pools on the
        # searched mesh. Exported docs and --explain carry it so the
        # explain path cannot report a mesh the runtime ignored.
        self.mesh_execution = "inherited"

    @property
    def tokens_per_s(self) -> float:
        return self.batch / self.cost.step_time if self.cost.step_time else 0.0

    def to_doc(self) -> dict:
        """Exportable summary of the search winner (embedded in the
        serving placement doc by compile_for_serving)."""
        return {
            "kind": "serving-search",
            "dp": self.dp,
            "tp": self.tp,
            "batch": self.batch,
            "kv_len": self.kv_len,
            "page_size": self.page_size,
            "step_time_us": self.cost.step_time * 1e6,
            "max_in_flight": self.max_in_flight,
            "max_in_flight_reserve": self.max_in_flight_reserve,
            "mesh_execution": self.mesh_execution,
            "fused_steps": self.fused_steps,
        }

    def describe(self) -> str:
        layout = f", pages of {self.page_size}" if self.page_size else ""
        if self.fused_steps > 1:
            layout += f", K={self.fused_steps} fused"
        fit = (
            f", ~{self.max_in_flight} seqs fit"
            if self.max_in_flight is not None
            else ""
        )
        if self.max_in_flight_reserve is not None:
            fit += f" ({self.max_in_flight_reserve} under reserve admission)"
        return (
            f"serving mesh(data={self.dp}, model={self.tp}) "
            f"[{self.mesh_execution}], batch "
            f"{self.batch}, kv {self.kv_len}{layout}: decode step "
            f"{self.cost.step_time * 1e6:.1f} us, "
            f"{self.tokens_per_s:.0f} tokens/s{fit}"
        )


def _serving_cache_geometry(graph: PCGGraph):
    """(mha_guids, heads, head_dim) of the graph's attention layers —
    the cache geometry the capacity estimate needs."""
    guids, geom = [], set()
    for g, node in graph.nodes.items():
        if node.op_type != OperatorType.MULTIHEAD_ATTENTION:
            continue
        guids.append(g)
        heads = int(node.params["num_heads"])
        geom.add((heads, int(node.params["embed_dim"]) // heads))
    if len(geom) != 1:
        raise ValueError(
            f"attention layers disagree on (heads, head_dim): {geom or '∅'}"
        )
    heads, head_dim = geom.pop()
    return tuple(guids), heads, head_dim


def resolve_decode_kernel(
    mode: str, graph: PCGGraph, kv_len: int, page_size: int = 0, w: int = 1
) -> str:
    """Resolve a ServeConfig.decode_kernel mode into the cost term to
    price ("pallas" or "dense") for this graph's cache geometry —
    the search-side mirror of the runtime selection in
    ops/pallas/decode_kernel.use_kernel, so optimize_serving and
    optimize_spec_k rank strategies with the cost shape the engine
    will actually run."""
    from flexflow_tpu.ops.pallas import decode_kernel as dk

    _, _, head_dim = _serving_cache_geometry(graph)
    if dk.use_kernel(mode, w, kv_len, head_dim, page_size):
        return "pallas"
    return "dense"


def estimate_max_in_flight(
    graph: PCGGraph,
    cache_bytes: int,
    mean_prompt_len: int,
    mean_gen_len: int,
    max_len: int,
    page_size: int = 0,
    tp: int = 1,
    itemsize: int = 4,
    admission: str = "optimistic",
    max_new_tokens: Optional[int] = None,
    kv_dtype: str = "fp32",
    prefix_hit_rate: float = 0.0,
) -> int:
    """How many concurrent sequences with the measured length profile
    (mean_prompt_len + mean_gen_len cached tokens each) fit in a
    per-chip KV byte budget.

    Prices the layout through KVCacheSpec.total_bytes (one-sequence
    spec): the slot layout charges every sequence max_len rows; the
    paged layout charges ceil((prompt + gen) / page_size) whole pages —
    the per-request footprint difference that lets paging admit more
    short requests at the same budget. TP over heads divides the
    per-chip row size, so a TP mesh fits proportionally more.

    `admission` picks WHICH per-sequence charge divides the budget:
    "optimistic" (the default, and the only policy a steady-state
    footprint can reach) charges each sequence the pages its profile
    actually fills; "reserve" charges the worst case the preemption-free
    gate holds back — prompt + the full `max_new_tokens` budget
    (defaulting to mean_gen_len, i.e. a workload that declares exactly
    what it uses). The ratio of the two answers is the concurrency
    headroom `--admission optimistic` unlocks on budget-declaring-but-
    short-finishing traffic (requests that reserve 256 tokens and emit
    20).

    `kv_dtype="int8"` prices the quantized paged pools: 1-byte K/V rows
    plus the fp32 per-(page, head) dequant scales in the side pools —
    just under 4x the sequences at the same budget. `prefix_hit_rate`
    (0..1) discounts the prompt bytes a shared-prefix workload never
    allocates: at hit rate h each admission charges (1-h)·prompt fresh
    tokens; the shared remainder maps refcounted pages another live
    request already paid for. The discount applies only to the
    "optimistic" charge — the reserve gate admits on worst-case
    divergence (every shared page may COW), so sharing buys it
    nothing."""
    from flexflow_tpu.serving.kv_cache import KVCacheSpec

    if admission not in ("reserve", "optimistic"):
        raise ValueError(
            f"admission must be 'reserve' or 'optimistic', got {admission!r}"
        )
    if kv_dtype not in ("fp32", "int8"):
        raise ValueError(f"kv_dtype must be 'fp32' or 'int8', got {kv_dtype!r}")
    if kv_dtype == "int8" and page_size <= 0:
        raise ValueError("kv_dtype='int8' requires a paged layout")
    if not 0.0 <= prefix_hit_rate <= 1.0:
        raise ValueError(
            f"prefix_hit_rate must be in [0, 1], got {prefix_hit_rate}"
        )
    if prefix_hit_rate and page_size <= 0:
        raise ValueError("prefix_hit_rate > 0 requires a paged layout")
    guids, heads, head_dim = _serving_cache_geometry(graph)
    heads_chip = max(1, heads // max(1, tp))
    if admission == "reserve":
        budget = max_new_tokens if max_new_tokens is not None else mean_gen_len
        seq_len = min(max_len, int(mean_prompt_len) + int(budget))
    else:
        fresh_prompt = int(round(mean_prompt_len * (1.0 - prefix_hit_rate)))
        seq_len = min(max_len, fresh_prompt + int(mean_gen_len))
    if page_size > 0:
        one = KVCacheSpec(
            layer_guids=guids,
            max_seqs=1,
            max_len=max_len,
            num_heads=heads_chip,
            head_dim=head_dim,
            buckets=(max_len,),
            page_size=page_size,
            num_pages=-(-max(1, seq_len) // page_size),
            itemsize=1 if kv_dtype == "int8" else itemsize,
            kv_dtype=kv_dtype,
        )
    else:
        one = KVCacheSpec(
            layer_guids=guids,
            max_seqs=1,
            max_len=max_len,
            num_heads=heads_chip,
            head_dim=head_dim,
            buckets=(max_len,),
            itemsize=itemsize,
        )
    per_seq = one.total_bytes
    return int(cache_bytes // per_seq) if per_seq else 0


def estimate_decode_step(
    graph: PCGGraph,
    cm: CostModel,
    dp: int,
    tp: int,
    batch: int,
    kv_len: int,
    page_size: int = 0,
    decode_kernel: str = "dense",
    kv_dtype: str = "fp32",
    fused_steps: int = 1,
    host_sync_s: float = 0.0,
) -> Optional[GraphCost]:
    """Cost one decode iteration of the whole PCG under a (dp, tp) mesh;
    None when infeasible (dp doesn't divide the batch, tp doesn't divide
    some sharded op's heads/columns, or the footprint overflows HBM).

    TP sync: each TP-sharded matmul chain resolves its partial sums with
    an all-reduce of the [batch/dp, features] activation. We charge one
    per attention node and one per linear node — an over-count of the
    Megatron column→row pairing (which needs one per PAIR), acceptable
    because decode activations are tiny and the verdict is driven by the
    weight-read term; the over-count only biases AGAINST tp, so a tp
    winner is a conservative conclusion.

    `host_sync_s` charges the host dispatch/reconcile round-trip every
    decode step pays, amortized over `fused_steps` when the
    device-resident multi-step loop fuses K iterations into one scan
    window (--decode-multistep) — the term optimize_serving minimizes
    to pick K. Defaults to 0.0 so every per-step caller (swap pricing,
    token-budget search) keeps its pure device cost."""
    if batch % dp != 0:
        return None
    b_chip = batch // dp
    compute = 0.0
    sync = 0.0
    mem = 0.0
    for node in graph.nodes.values():
        if node.op_type == OperatorType.INPUT or node.is_parallel_op:
            continue
        width = _DECODE_TP_OPS.get(node.op_type)
        node_tp = tp
        if width is not None and tp > 1:
            if width(node) % tp != 0:
                return None
        elif width is None:
            node_tp = 1
        c = cm.decode_op_cost(
            node, b_chip, kv_len, tp=node_tp, page_size=page_size,
            kernel=decode_kernel, kv_dtype=kv_dtype,
        )
        compute += c.forward_time
        mem += c.memory
        if node_tp > 1 and node.output_shapes:
            out = node.output_shapes[0]
            act = b_chip * out.logical_sizes[-1] * cm.elem_bytes(out)
            sync += cm.all_reduce(float(act), node_tp)
    host = float(host_sync_s) / max(1, int(fused_steps))
    cost = GraphCost(
        step_time=compute + sync + host,
        compute_time=compute,
        sync_time=sync + host,
        memory_per_chip=int(mem),
    )
    return cost


def estimate_verify_step(
    graph: PCGGraph,
    cm: CostModel,
    dp: int,
    tp: int,
    batch: int,
    kv_len: int,
    k: int,
    page_size: int = 0,
    decode_kernel: str = "dense",
    kv_dtype: str = "fp32",
    tree_nodes: int = 0,
) -> Optional[GraphCost]:
    """Cost one speculative-decoding VERIFY iteration (k+1 scored token
    positions per sequence, serving/engine.verify) of the whole PCG
    under a (dp, tp) mesh — the spec-decode twin of estimate_decode_step
    (same feasibility rules, same conservative one-all-reduce-per-node
    TP sync charge; the synced activation is (k+1)x wider).
    tree_nodes > 0 prices the token-tree verify's 1 + tree_nodes rows
    instead (CostModel.verify_op_cost's tree_nodes)."""
    if batch % dp != 0:
        return None
    b_chip = batch // dp
    compute = 0.0
    sync = 0.0
    mem = 0.0
    for node in graph.nodes.values():
        if node.op_type == OperatorType.INPUT or node.is_parallel_op:
            continue
        width = _DECODE_TP_OPS.get(node.op_type)
        node_tp = tp
        if width is not None and tp > 1:
            if width(node) % tp != 0:
                return None
        elif width is None:
            node_tp = 1
        c = cm.verify_op_cost(
            node, b_chip, kv_len, k, tp=node_tp, page_size=page_size,
            kernel=decode_kernel, kv_dtype=kv_dtype, tree_nodes=tree_nodes,
        )
        compute += c.forward_time
        mem += c.memory
        if node_tp > 1 and node.output_shapes:
            out = node.output_shapes[0]
            w = (1 + tree_nodes) if tree_nodes > 0 else (k + 1)
            act = b_chip * w * out.logical_sizes[-1] * cm.elem_bytes(out)
            sync += cm.all_reduce(float(act), node_tp)
    return GraphCost(
        step_time=compute + sync,
        compute_time=compute,
        sync_time=sync,
        memory_per_chip=int(mem),
    )


def estimate_recompute_step(
    graph: PCGGraph,
    cm: CostModel,
    dp: int,
    tp: int,
    resume_len: int,
    page_size: int = 0,
    decode_kernel: str = "dense",
) -> Optional[GraphCost]:
    """Cost of recovering ONE preempted sequence by recompute: a single
    prefill-shaped pass over its prompt + generated-so-far
    (`resume_len` positions) against an empty cache — what the
    scheduler's preemption-by-recompute path actually runs
    (serving/scheduler.py re-admission). Optimistic admission pays this
    per preemption event where the reserve policy pays nothing; weigh
    it against the extra concurrency estimate_max_in_flight reports and
    the workload's expected preemption rate. Same feasibility rules as
    estimate_decode_step; None when (dp, tp) is infeasible."""
    if resume_len < 1:
        raise ValueError(f"resume_len must be >= 1, got {resume_len}")
    compute = 0.0
    sync = 0.0
    mem = 0.0
    for node in graph.nodes.values():
        if node.op_type == OperatorType.INPUT or node.is_parallel_op:
            continue
        width = _DECODE_TP_OPS.get(node.op_type)
        node_tp = tp
        if width is not None and tp > 1:
            if width(node) % tp != 0:
                return None
        elif width is None:
            node_tp = 1
        c = cm.prefill_op_cost(
            node, 1, resume_len, tp=node_tp, page_size=page_size,
            kernel=decode_kernel,
        )
        compute += c.forward_time
        mem += c.memory
        if node_tp > 1 and node.output_shapes:
            out = node.output_shapes[0]
            act = resume_len * out.logical_sizes[-1] * cm.elem_bytes(out)
            sync += cm.all_reduce(float(act), node_tp)
    return GraphCost(
        step_time=compute + sync,
        compute_time=compute,
        sync_time=sync,
        memory_per_chip=int(mem),
    )


def expected_accepted_tokens(acceptance_rate: float, k: int) -> float:
    """E[accepted drafts] of a k-token draft under a per-token
    acceptance rate α (independence approximation: the verify accepts a
    geometric prefix, so E = Σ_{i=1..k} α^i). The verify then emits one
    MORE token from the target itself (correction or bonus), so
    expected tokens per verify step is this plus one."""
    a = min(max(float(acceptance_rate), 0.0), 1.0)
    if a >= 1.0:
        return float(k)
    return a * (1.0 - a**k) / (1.0 - a)


class SpecKResult:
    """The draft length optimize_spec_k picked, with the priced
    alternatives. k == 0 means speculation does not pay at this
    acceptance rate (the draft/verify overhead exceeds the accepted
    tokens' worth)."""

    def __init__(
        self,
        k: int,
        acceptance_rate: float,
        tokens_per_s: float,
        decode_tokens_per_s: float,
        step_time: float,
        tokens_per_step: float,
    ):
        self.k = k
        self.acceptance_rate = acceptance_rate
        self.tokens_per_s = tokens_per_s
        self.decode_tokens_per_s = decode_tokens_per_s
        self.step_time = step_time
        self.tokens_per_step = tokens_per_step

    @property
    def speedup(self) -> float:
        """Expected decode-throughput ratio over non-speculative decode."""
        if not self.decode_tokens_per_s:
            return 1.0
        return self.tokens_per_s / self.decode_tokens_per_s

    def describe(self) -> str:
        return (
            f"spec-k {self.k} at acceptance {self.acceptance_rate:.2f}: "
            f"{self.tokens_per_step:.2f} tokens/step, expected "
            f"{self.speedup:.2f}x over plain decode"
        )


def optimize_spec_k(
    graph: PCGGraph,
    spec: MachineSpec,
    acceptance_rate: float,
    batch: int = 1,
    kv_len: int = 1024,
    k_max: int = 8,
    draft_graph: Optional[PCGGraph] = None,
    dp: int = 1,
    tp: int = 1,
    page_size: int = 0,
    machine_model=None,
    mixed_precision: bool = False,
    decode_kernel: str = "dense",
) -> SpecKResult:
    """Pick the draft length k that maximizes expected decode throughput
    at a MEASURED per-token acceptance rate (SchedulerStats
    .acceptance_rate from a spec-mode run, or an offline estimate).

    Prices each candidate k as: one verify step of k+1 positions
    (CostModel.verify_op_cost — weights stream once, the spec-decode
    win) plus the draft cost (k decode steps of `draft_graph` when the
    draft is a model; zero for the weight-free n-gram draft), buying
    1 + E[accepted](α, k) tokens. k = 0 (plain decode) is always a
    candidate, so a hopeless acceptance rate yields "don't speculate"
    rather than a forced k."""
    cm = CostModel(
        spec,
        measure=False,
        machine_model=machine_model,
        mixed_precision=mixed_precision,
    )
    base = estimate_decode_step(
        graph, cm, dp, tp, batch, kv_len, page_size=page_size,
        decode_kernel=decode_kernel,
    )
    if base is None:
        raise ValueError(f"(dp={dp}, tp={tp}) is infeasible for this graph")
    draft_step = 0.0
    if draft_graph is not None:
        d = estimate_decode_step(
            draft_graph, cm, dp, tp, batch, kv_len,
            decode_kernel=decode_kernel,
        )
        if d is None:
            raise ValueError(
                f"(dp={dp}, tp={tp}) is infeasible for the draft graph"
            )
        draft_step = d.step_time
    decode_rate = batch / base.step_time if base.step_time else 0.0
    best = SpecKResult(
        0, acceptance_rate, decode_rate, decode_rate, base.step_time, 1.0
    )
    for k in range(1, k_max + 1):
        vcost = estimate_verify_step(
            graph, cm, dp, tp, batch, kv_len, k, page_size=page_size,
            decode_kernel=decode_kernel,
        )
        if vcost is None:
            continue
        step_time = vcost.step_time + k * draft_step
        tokens = 1.0 + expected_accepted_tokens(acceptance_rate, k)
        rate = batch * tokens / step_time if step_time else 0.0
        if rate > best.tokens_per_s:
            best = SpecKResult(
                k, acceptance_rate, rate, decode_rate, step_time, tokens
            )
    return best


def expected_accepted_tree_tokens(
    acceptance_rate: float, depth: int, branch: int
) -> float:
    """E[accepted root-to-leaf path length] of a (depth, branch) token
    tree under a per-token acceptance rate α. A level survives when ANY
    of its `branch` alternatives matches — α_b = 1 - (1-α)^branch under
    the independence approximation — and the accepted path is a
    geometric prefix of levels, so E = Σ_{i=1..depth} α_b^i. branch = 1
    reduces exactly to expected_accepted_tokens."""
    a = min(max(float(acceptance_rate), 0.0), 1.0)
    ab = 1.0 - (1.0 - a) ** max(1, int(branch))
    if ab >= 1.0:
        return float(depth)
    return ab * (1.0 - ab ** int(depth)) / (1.0 - ab)


class SpecTreeResult:
    """The (depth, branch) draft-tree shape optimize_spec_tree picked.
    branch == 1 means a tree does not pay at this acceptance profile
    (the extra verified nodes cost more than the per-level retry is
    worth) — run the linear chain; depth == 0 means speculation itself
    does not pay."""

    def __init__(
        self,
        depth: int,
        branch: int,
        acceptance_rate: float,
        tokens_per_s: float,
        decode_tokens_per_s: float,
        step_time: float,
        tokens_per_step: float,
    ):
        self.depth = depth
        self.branch = branch
        self.acceptance_rate = acceptance_rate
        self.tokens_per_s = tokens_per_s
        self.decode_tokens_per_s = decode_tokens_per_s
        self.step_time = step_time
        self.tokens_per_step = tokens_per_step

    @property
    def nodes(self) -> int:
        """Verify node budget (tree width minus the root row)."""
        return self.depth * self.branch

    @property
    def speedup(self) -> float:
        if not self.decode_tokens_per_s:
            return 1.0
        return self.tokens_per_s / self.decode_tokens_per_s

    def describe(self) -> str:
        return (
            f"spec-tree depth {self.depth} x branch {self.branch} "
            f"({self.nodes} nodes) at acceptance "
            f"{self.acceptance_rate:.2f}: {self.tokens_per_step:.2f} "
            f"tokens/step, expected {self.speedup:.2f}x over plain decode"
        )


def optimize_spec_tree(
    graph: PCGGraph,
    spec: MachineSpec,
    acceptance_rate: float,
    batch: int = 1,
    kv_len: int = 1024,
    depth_max: int = 8,
    branch_max: int = 4,
    draft_graph: Optional[PCGGraph] = None,
    dp: int = 1,
    tp: int = 1,
    page_size: int = 0,
    machine_model=None,
    mixed_precision: bool = False,
    decode_kernel: str = "dense",
) -> SpecTreeResult:
    """Pick the draft-tree shape (depth, branching factor) that
    maximizes expected decode throughput at a MEASURED per-token
    acceptance rate — the tree twin of optimize_spec_k.

    Prices each (d, b) candidate as: one tree verify of 1 + d*b rows
    (estimate_verify_step with tree_nodes — every node is a scored row
    and a fresh cache row, whatever the topology) plus the draft cost
    (d draft decode steps for a model draft: the spine is decoded once
    and the sibling alternates come from the SAME logits, so branching
    is draft-free; zero for the n-gram draft), buying
    1 + E[path](α, d, b) tokens. (d, 1) candidates subsume the linear
    chain and (0, 1) plain decode, so a profile where trees don't pay
    degrades to optimize_spec_k's answer rather than a forced tree."""
    cm = CostModel(
        spec,
        measure=False,
        machine_model=machine_model,
        mixed_precision=mixed_precision,
    )
    base = estimate_decode_step(
        graph, cm, dp, tp, batch, kv_len, page_size=page_size,
        decode_kernel=decode_kernel,
    )
    if base is None:
        raise ValueError(f"(dp={dp}, tp={tp}) is infeasible for this graph")
    draft_step = 0.0
    if draft_graph is not None:
        d = estimate_decode_step(
            draft_graph, cm, dp, tp, batch, kv_len,
            decode_kernel=decode_kernel,
        )
        if d is None:
            raise ValueError(
                f"(dp={dp}, tp={tp}) is infeasible for the draft graph"
            )
        draft_step = d.step_time
    decode_rate = batch / base.step_time if base.step_time else 0.0
    best = SpecTreeResult(
        0, 1, acceptance_rate, decode_rate, decode_rate, base.step_time, 1.0
    )
    for depth in range(1, depth_max + 1):
        for branch in range(1, branch_max + 1):
            vcost = estimate_verify_step(
                graph, cm, dp, tp, batch, kv_len, depth,
                page_size=page_size, decode_kernel=decode_kernel,
                tree_nodes=depth * branch,
            )
            if vcost is None:
                continue
            step_time = vcost.step_time + depth * draft_step
            tokens = 1.0 + expected_accepted_tree_tokens(
                acceptance_rate, depth, branch
            )
            rate = batch * tokens / step_time if step_time else 0.0
            if rate > best.tokens_per_s:
                best = SpecTreeResult(
                    depth,
                    branch,
                    acceptance_rate,
                    rate,
                    decode_rate,
                    step_time,
                    tokens,
                )
    return best


def estimate_chunk_step(
    graph: PCGGraph,
    cm: CostModel,
    dp: int,
    tp: int,
    batch: int,
    cursor: int,
    chunk: int,
    page_size: int = 0,
    decode_kernel: str = "dense",
) -> Optional[GraphCost]:
    """Cost one chunked-prefill step of the whole PCG under a (dp, tp)
    mesh: `chunk` prompt positions appended at cache cursor `cursor`
    for each of `batch` chunking sequences — the chunk twin of
    estimate_verify_step (a chunk IS a verify with nothing to accept),
    priced through CostModel.prefill_chunk_cost. Same feasibility rules
    and conservative one-all-reduce-per-node TP sync charge."""
    if batch % dp != 0:
        return None
    b_chip = batch // dp
    compute = 0.0
    sync = 0.0
    mem = 0.0
    for node in graph.nodes.values():
        if node.op_type == OperatorType.INPUT or node.is_parallel_op:
            continue
        width = _DECODE_TP_OPS.get(node.op_type)
        node_tp = tp
        if width is not None and tp > 1:
            if width(node) % tp != 0:
                return None
        elif width is None:
            node_tp = 1
        c = cm.prefill_chunk_cost(
            node, b_chip, cursor, chunk, tp=node_tp, page_size=page_size,
            kernel=decode_kernel,
        )
        compute += c.forward_time
        mem += c.memory
        if node_tp > 1 and node.output_shapes:
            out = node.output_shapes[0]
            act = b_chip * chunk * out.logical_sizes[-1] * cm.elem_bytes(out)
            sync += cm.all_reduce(float(act), node_tp)
    return GraphCost(
        step_time=compute + sync,
        compute_time=compute,
        sync_time=sync,
        memory_per_chip=int(mem),
    )


class TokenBudgetResult:
    """The per-iteration token budget optimize_token_budget picked,
    with the prediction it was picked on. `meets_slo` reports whether
    the predicted latencies clear the thresholds — False means no
    candidate could, and the returned budget is the least-violating
    one (scheduling cannot beat physics: if one decode iteration
    already exceeds slo_itl_ms, no budget fixes it)."""

    def __init__(
        self,
        token_budget: int,
        chunk_size: int,
        predicted_ttft_s: float,
        predicted_itl_s: float,
        n_chunks: int,
        meets_slo: bool,
        slo_ttft_s: float,
        slo_itl_s: float,
    ):
        self.token_budget = token_budget
        self.chunk_size = chunk_size
        self.predicted_ttft_s = predicted_ttft_s
        self.predicted_itl_s = predicted_itl_s
        self.n_chunks = n_chunks
        self.meets_slo = meets_slo
        self.slo_ttft_s = slo_ttft_s
        self.slo_itl_s = slo_itl_s

    def describe(self) -> str:
        verdict = "meets SLO" if self.meets_slo else "SLO infeasible"
        return (
            f"token-budget {self.token_budget} (chunk {self.chunk_size}, "
            f"{self.n_chunks} chunks): predicted TTFT "
            f"{self.predicted_ttft_s * 1e3:.2f} ms, ITL "
            f"{self.predicted_itl_s * 1e3:.2f} ms — {verdict}"
        )


def optimize_token_budget(
    graph: PCGGraph,
    spec: MachineSpec,
    prompt_len: int,
    batch: int = 1,
    kv_len: int = 1024,
    chunk_size: int = 16,
    slo_ttft_ms: float = 0.0,
    slo_itl_ms: float = 0.0,
    dp: int = 1,
    tp: int = 1,
    page_size: int = 0,
    machine_model=None,
    mixed_precision: bool = False,
    decode_kernel: str = "dense",
    measured_decode_step_s: float = 0.0,
) -> TokenBudgetResult:
    """Pick the smallest per-iteration token budget whose PREDICTED
    p95 latencies meet the SLO thresholds — the enforcement half of the
    SLO story (PR 8's rolling `serve_slo_*` windows are the
    measurement half; `--slo-ttft-ms`/`--slo-itl-ms` feed both).

    The model mirrors the scheduler's fair-share planner: with `batch`
    decodes in flight (1 token each, reserved first), a budget B leaves
    floor((B - batch) / chunk_size) chunk_size-units per iteration for
    a `prompt_len` prompt, so the prompt lands in n_chunks iterations.
    Each iteration is priced as one decode step over the in-flight
    batch (estimate_decode_step) plus one chunk step at the advancing
    cursor (estimate_chunk_step / CostModel.prefill_chunk_cost):
    predicted TTFT = Σ iterations until the last chunk, predicted ITL =
    the widest single iteration a decode waits through. Smaller budgets
    lower ITL and raise TTFT; the smallest feasible budget is the
    SLO-safest point of that trade. When NO budget meets both
    thresholds the least-violating one returns with meets_slo=False.

    `measured_decode_step_s` calibrates the analytic clock against a
    measured per-iteration time (the rolling ITL window's p95 from an
    unchunked run, or SchedulerStats.mean_dispatch_gap_s): every
    predicted time scales by measured / analytic-decode-step, so the
    roofline model contributes the RATIOS between candidate budgets
    while the measurement pins the absolute scale — measure-then-decide
    applied to the scheduler itself."""
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    cm = CostModel(
        spec,
        measure=False,
        machine_model=machine_model,
        mixed_precision=mixed_precision,
    )
    dec_batch = max(0, int(batch))
    t_dec = 0.0
    if dec_batch:
        base = estimate_decode_step(
            graph, cm, dp, tp, dec_batch, kv_len, page_size=page_size,
            decode_kernel=decode_kernel,
        )
        if base is None:
            raise ValueError(
                f"(dp={dp}, tp={tp}) is infeasible for this graph"
            )
        t_dec = base.step_time
    scale = 1.0
    if measured_decode_step_s > 0.0 and t_dec > 0.0:
        scale = measured_decode_step_s / t_dec
    slo_ttft_s = slo_ttft_ms / 1e3
    slo_itl_s = slo_itl_ms / 1e3
    n_units_max = -(-prompt_len // chunk_size)
    best: Optional[TokenBudgetResult] = None
    best_score = float("inf")
    for m in range(1, n_units_max + 1):
        c = m * chunk_size  # chunk tokens granted per iteration
        budget = dec_batch + c
        n_chunks = -(-prompt_len // c)
        ttft = 0.0
        itl = t_dec
        for i in range(n_chunks):
            cursor = i * c
            w = min(c, prompt_len - cursor)
            ch = estimate_chunk_step(
                graph, cm, dp, tp, 1, cursor, w, page_size=page_size,
                decode_kernel=decode_kernel,
            )
            if ch is None:
                raise ValueError(
                    f"(dp={dp}, tp={tp}) is infeasible for this graph"
                )
            ttft += t_dec + ch.step_time
            itl = max(itl, t_dec + ch.step_time)
        ttft *= scale
        itl *= scale
        # score: worst normalized SLO ratio (an unset threshold does
        # not constrain); <= 1 means both thresholds are met
        score = 0.0
        if slo_ttft_s:
            score = max(score, ttft / slo_ttft_s)
        if slo_itl_s:
            score = max(score, itl / slo_itl_s)
        cand = TokenBudgetResult(
            token_budget=budget,
            chunk_size=chunk_size,
            predicted_ttft_s=ttft,
            predicted_itl_s=itl,
            n_chunks=n_chunks,
            meets_slo=score <= 1.0,
            slo_ttft_s=slo_ttft_s,
            slo_itl_s=slo_itl_s,
        )
        if cand.meets_slo:
            # smallest feasible budget: the SLO-safest point — later
            # (larger) candidates only raise the per-iteration stall
            return cand
        if score < best_score:
            best, best_score = cand, score
    assert best is not None  # m = 1 always produced a candidate
    return best


def optimize_token_budget_per_class(
    graph: PCGGraph,
    spec: MachineSpec,
    prompt_len: int,
    classes,
    batch: int = 1,
    kv_len: int = 1024,
    chunk_size: int = 16,
    dp: int = 1,
    tp: int = 1,
    page_size: int = 0,
    machine_model=None,
    mixed_precision: bool = False,
    decode_kernel: str = "dense",
    measured_decode_step_s: float = 0.0,
):
    """Per-priority-class `optimize_token_budget`: size ONE shared
    iteration budget against the tightest SLO of every configured class.

    `classes` is the ``{name: PriorityClass}`` mapping from
    ``serving.tenancy.parse_classes`` (duck-typed here — any object with
    ``slo_ttft_ms``/``slo_itl_ms`` works, so search stays import-free of
    serving). Each class is solved independently with its own
    thresholds; the scheduler runs a single planner loop, so the
    returned budget is the max over per-class answers (the class that
    needs the most chunk throughput to hit its TTFT wins) and
    ``meets_slo`` only if every class's own solve met its thresholds at
    that shared operating point. Returns ``(budget, meets_slo,
    {name: TokenBudgetResult})``; classes with no thresholds set are
    observe-only and never constrain."""
    per_class: Dict[str, TokenBudgetResult] = {}
    for name, cls in classes.items():
        per_class[name] = optimize_token_budget(
            graph,
            spec,
            prompt_len,
            batch=batch,
            kv_len=kv_len,
            chunk_size=chunk_size,
            slo_ttft_ms=float(getattr(cls, "slo_ttft_ms", 0.0)),
            slo_itl_ms=float(getattr(cls, "slo_itl_ms", 0.0)),
            dp=dp,
            tp=tp,
            page_size=page_size,
            machine_model=machine_model,
            mixed_precision=mixed_precision,
            decode_kernel=decode_kernel,
            measured_decode_step_s=measured_decode_step_s,
        )
    if not per_class:
        raise ValueError("classes must be a non-empty mapping")
    budget = max(r.token_budget for r in per_class.values())
    meets = all(r.meets_slo for r in per_class.values())
    return budget, meets, per_class


def optimize_serving(
    graph: PCGGraph,
    num_devices: int,
    spec: MachineSpec,
    batch_size: int = 1,
    kv_len: int = 1024,
    mixed_precision: bool = False,
    machine_model=None,
    verbose: bool = False,
    page_size: int = 0,
    mean_prompt_len: Optional[int] = None,
    mean_gen_len: Optional[int] = None,
    max_len: Optional[int] = None,
    decode_kernel: str = "dense",
    max_new_tokens: Optional[int] = None,
    kv_dtype: str = "fp32",
    prefix_hit_rate: float = 0.0,
    max_fused_steps: int = 1,
    host_sync_s: float = DECODE_HOST_SYNC_S,
) -> ServingSearchResult:
    """Pick the decode-latency-optimal (dp, tp) mesh for serving
    `batch_size` concurrent sequences at `kv_len` cache positions.
    Enumerates every (dp, tp) with dp·tp dividing the chip count (idle
    chips allowed, mirroring the training search's idle-dp candidates) and
    keeps the feasible minimum-step-time one.

    page_size > 0 prices the paged KV layout (per-sequence reads round
    up to whole pages); decode_kernel ("pallas" | "dense", resolve a
    ServeConfig mode via resolve_decode_kernel) selects the attention
    core's cost shape — the kernel's single page-granular pool read vs
    the dense fallback's gather. When a measured length profile is
    supplied
    (mean_prompt_len + mean_gen_len), the winner also carries
    `max_in_flight`: how many such sequences fit in the winning mesh's
    leftover HBM (chip capacity minus its weight shard, through
    KVCacheSpec.total_bytes) — the "how many sequences fit" answer that
    turns page geometry into a capacity verdict. Supplying
    `max_new_tokens` (the per-request generation BUDGET, as opposed to
    the mean actually generated) additionally fills
    `max_in_flight_reserve` — the same budget under the preemption-free
    reserve admission gate, so the result compares what
    `--admission optimistic` buys over `reserve` on this workload.
    `kv_dtype` and `prefix_hit_rate` reprice the capacity estimates for
    the quantized pools (--kv-dtype int8) and a shared-prefix workload
    (--prefix-cache at measured hit rate h): see
    estimate_max_in_flight — the decode step-time cost itself also
    shifts under int8 (thinner pool reads, extra scale reads), priced
    through CostModel.decode_op_cost's kv_dtype term.

    `max_fused_steps` > 1 additionally enumerates the device-resident
    multi-step window depth K (powers of two up to the cap, matching
    the engine's K-bucketing): each candidate's step time carries the
    `host_sync_s` round-trip amortized over K
    (estimate_decode_step's fused_steps term), and — when mean_gen_len
    is known — a retire-waste factor 1 + (K-1)/(2·mean_gen_len) for
    the window tail an EOS discards on average, so the optimal K is a
    real trade-off rather than always-the-cap. The winner carries its
    K as `fused_steps` (--max-fused-steps takes it from the doc)."""
    cm = CostModel(
        spec,
        measure=False,  # the measured table times training shapes
        machine_model=machine_model,
        mixed_precision=mixed_precision,
    )
    fused_cands = [1]
    while max_fused_steps >= fused_cands[-1] * 2:
        fused_cands.append(fused_cands[-1] * 2)
    best: Optional[ServingSearchResult] = None
    best_eff = float("inf")
    for used in range(1, num_devices + 1):
        if num_devices % used != 0:
            continue
        for dp, tp in _mesh_factorizations(used):
            for kf in fused_cands:
                cost = estimate_decode_step(
                    graph, cm, dp, tp, batch_size, kv_len,
                    page_size=page_size, decode_kernel=decode_kernel,
                    kv_dtype=kv_dtype, fused_steps=kf,
                    host_sync_s=host_sync_s if max_fused_steps > 1 else 0.0,
                )
                if cost is None or not cost.feasible(spec):
                    continue
                waste = (
                    1.0 + (kf - 1) / (2.0 * mean_gen_len)
                    if mean_gen_len
                    else 1.0
                )
                eff = cost.step_time * waste
                cur = ServingSearchResult(
                    dp, tp, batch_size, kv_len, cost,
                    page_size=page_size, fused_steps=kf,
                )
                if verbose:
                    print(f"[serve-search] {cur.describe()}")
                if best is None or eff < best_eff:
                    best, best_eff = cur, eff
    if best is None:
        raise RuntimeError("serving search found no feasible strategy")
    if mean_prompt_len is not None and mean_gen_len is not None:
        horizon = max_len if max_len is not None else kv_len
        weight_bytes = 0.0
        for node in graph.nodes.values():
            if node.op_type == OperatorType.INPUT or node.is_parallel_op:
                continue
            node_tp = best.tp if _DECODE_TP_OPS.get(node.op_type) else 1
            weight_bytes += (
                sum(s.volume() * cm.elem_bytes(s) for s in node.weight_shapes)
                / node_tp
            )
        budget = max(0, spec.hbm_bytes - int(weight_bytes))
        best.max_in_flight = estimate_max_in_flight(
            graph,
            budget,
            mean_prompt_len,
            mean_gen_len,
            horizon,
            page_size=page_size,
            tp=best.tp,
            kv_dtype=kv_dtype,
            prefix_hit_rate=prefix_hit_rate,
        )
        if max_new_tokens is not None:
            best.max_in_flight_reserve = estimate_max_in_flight(
                graph,
                budget,
                mean_prompt_len,
                mean_gen_len,
                horizon,
                page_size=page_size,
                tp=best.tp,
                admission="reserve",
                max_new_tokens=max_new_tokens,
                kv_dtype=kv_dtype,
            )
    return best


def search_serving_strategy(
    model,
    batch_size: int = 1,
    kv_len: Optional[int] = None,
    mean_prompt_len: Optional[int] = None,
    mean_gen_len: Optional[int] = None,
    max_new_tokens: Optional[int] = None,
    prefix_hit_rate: Optional[float] = None,
) -> ServingSearchResult:
    """Model-level entry: cost the compiled builder graph's decode regime
    on the config's machine (chip/nodes like the training search). kv_len
    defaults to the config's serving cache length; the KV layout and page
    geometry come from the config's --kv-layout/--kv-page-size flags, the
    attention core's cost shape from --decode-kernel (resolved against
    the graph's cache geometry exactly like the engine resolves it), and
    a supplied length profile fills the winner's max_in_flight capacity
    estimate. The capacity estimate prices the config's --kv-dtype, and
    `prefix_hit_rate` (workload-measured; defaults to 0, and is only
    honored when --prefix-cache is on) discounts shared prompt bytes."""
    from flexflow_tpu.serving.kv_cache import default_page_size

    cfg = model.config
    page_size = 0
    if getattr(cfg, "serve_kv_layout", "paged") == "paged":
        page_size = cfg.serve_kv_page_size or default_page_size(
            cfg.serve_max_seq_len
        )
    decode_kernel = resolve_decode_kernel(
        getattr(cfg, "serve_decode_kernel", "auto"),
        model.graph,
        cfg.serve_max_seq_len,
        page_size=page_size,
    )
    n = cfg.num_devices if cfg.workers_per_node > 0 else None
    if n is None:
        import jax

        n = len(jax.devices())
    spec = MachineSpec(
        num_nodes=max(1, cfg.num_nodes),
        chips_per_node=max(1, n // max(1, cfg.num_nodes)),
        chip=cfg.chip,
    )
    return optimize_serving(
        model.graph,
        n,
        spec,
        batch_size=batch_size,
        kv_len=kv_len if kv_len is not None else cfg.serve_max_seq_len,
        mixed_precision=cfg.allow_mixed_precision,
        page_size=page_size,
        mean_prompt_len=mean_prompt_len,
        mean_gen_len=mean_gen_len,
        max_len=cfg.serve_max_seq_len,
        decode_kernel=decode_kernel,
        max_new_tokens=max_new_tokens,
        kv_dtype=getattr(cfg, "serve_kv_dtype", "fp32"),
        prefix_hit_rate=(
            prefix_hit_rate or 0.0
            if getattr(cfg, "serve_prefix_cache", False)
            else 0.0
        ),
        max_fused_steps=(
            int(getattr(cfg, "serve_max_fused_steps", 1))
            if getattr(cfg, "serve_decode_multistep", False)
            else 1
        ),
    )


def _record_search_result_trace(trace, sr: SearchResult, spec) -> None:
    """Record a SearchResult (mesh / extra-axis winner) as the trace's
    result. Mesh strategies have no per-op view map, so the breakdown is
    the GraphCost aggregate and the whole total rides the residual —
    the explain identity (sum(ops) + residual == total) still holds."""
    c = sr.cost
    descr = sr.describe()  # fresh string — rows hold no live state
    trace.result(
        total_cost=c.step_time,
        ops=[],
        residual=c.step_time,
        kind=sr.kind,
        name=descr,
        dp=sr.dp,
        compute_time=c.compute_time,
        comm_time=c.comm_time,
        sync_time=c.sync_time,
        update_time=c.update_time,
        memory_per_chip=float(c.memory_per_chip),
        feasible=bool(c.feasible(spec)),
    )


def search_strategy(model, num_devices: int) -> Strategy:
    """compile()-time entry (reference: graph_optimize_task,
    graph.cc:1545-1613)."""
    cfg = model.config
    # search-without-hardware overrides (reference: model.cc:3673-3680)
    n = num_devices
    if cfg.search_num_workers > 0:
        n = cfg.search_num_workers * max(1, cfg.search_num_nodes)
    spec = MachineSpec(
        num_nodes=max(1, cfg.search_num_nodes)
        if cfg.search_num_nodes > 0
        else max(1, cfg.num_nodes),
        chips_per_node=max(1, n // max(1, cfg.num_nodes)),
        chip=cfg.chip,
    )
    if n <= 1:
        # nothing to search on one device — but a requested trace must
        # still produce a valid artifact (a silently-missing export
        # breaks explain/CI workflows on single-chip boxes)
        if cfg.search_trace_file or cfg.search_explain:
            from flexflow_tpu.telemetry.search_trace import SearchTrace

            trace = SearchTrace(
                engine=cfg.search_engine, path=cfg.search_trace_file
            )
            trace.header(
                engine=cfg.search_engine, seed=cfg.seed,
                budget=cfg.search_budget, measure=bool(cfg.measure_costs),
            )
            trace.event("search_skipped", reason="single device")
            trace.result(
                total_cost=0.0, ops=[], residual=0.0,
                kind="data-parallel",
                name="data-parallel (single device — search skipped)",
            )
            model.search_trace = trace
            if cfg.search_trace_file:
                trace.save()
            if cfg.search_explain:
                from flexflow_tpu.search.explain import explain_strategy

                print(explain_strategy(trace.rows()).text())
        return data_parallel_strategy(num_devices, model.graph)

    if cfg.search_engine not in ("mesh", "unity", "mcmc"):
        raise ValueError(
            f"unknown --search-engine {cfg.search_engine!r}; "
            "expected mesh | unity | mcmc"
        )
    from flexflow_tpu.search.machine_model import build_machine_model

    mm = build_machine_model(cfg, spec)
    sparse_ok = cfg.sparse_embedding_update and (
        model.optimizer is None or model.optimizer.supports_sparse()
    )
    # search observability (--search-trace / --explain): one SearchTrace
    # threads through whichever engine runs; the exported JSONL +
    # timeline reconstruct every candidate considered, and the explain
    # report reconstructs why the winner won (search/explain.py)
    trace = None
    if cfg.search_trace_file or cfg.search_explain:
        from flexflow_tpu.telemetry.search_trace import SearchTrace

        trace = SearchTrace(
            engine=cfg.search_engine, path=cfg.search_trace_file
        )
        n_nodes = len(model.graph.nodes)  # scalar precomputed: trace
        # rows must not touch live graph state (fxlint FX104)
        trace.header(
            engine=cfg.search_engine,
            seed=cfg.seed,
            budget=cfg.search_budget,
            alpha=cfg.search_alpha,
            measure=bool(cfg.measure_costs),
            machine={
                "num_nodes": spec.num_nodes,
                "chips_per_node": spec.chips_per_node,
                "chip": spec.chip,
            },
            graph={
                "nodes": n_nodes,
                "batch_size": cfg.batch_size,
            },
        )
        model.search_trace = trace

    def _finish_trace() -> None:
        """Export + explain once the winner is known."""
        if trace is None:
            return
        if cfg.search_trace_file:
            trace.save()
        if cfg.search_explain:
            from flexflow_tpu.search.explain import explain_strategy

            print(explain_strategy(trace.rows()).text())
    if cfg.search_engine in ("unity", "mcmc"):
        from flexflow_tpu.search import unity as unity_mod

        if cfg.search_engine == "unity":
            result = unity_mod.UnitySearch(
                model.graph,
                spec,
                machine_model=mm,
                mixed_precision=cfg.allow_mixed_precision,
                measure=cfg.measure_costs,
                calibration_file=cfg.calibration_file,
                sparse_embedding=sparse_ok,
                trace=trace,
            ).optimize()
        else:
            from flexflow_tpu.search.mcmc import mcmc_optimize

            result = mcmc_optimize(
                model.graph,
                spec,
                budget=max(cfg.search_budget, 1),
                alpha=cfg.search_alpha,
                seed=cfg.seed,
                verbose=cfg.profiling,
                machine_model=mm,
                mixed_precision=cfg.allow_mixed_precision,
                measure=cfg.measure_costs,
                calibration_file=cfg.calibration_file,
                sparse_embedding=sparse_ok,
                trace=trace,
            )
        # every engine must cover the whole strategy space the runtime
        # executes (VERDICT r2 item 6; the reference has one search over
        # everything its runtime does, substitution.cc:1721-1862): before
        # answering, compare the engine's (dp, ch)-grid winner against
        # the pipeline/seq/spatial/mixed candidates
        cm_extra = CostModel(
            spec,
            measure=cfg.measure_costs,
            machine_model=mm,
            mixed_precision=cfg.allow_mixed_precision,
            calibration_file=cfg.calibration_file,
            sparse_embedding=sparse_ok,
        )
        extra, _ = extra_axis_candidates(
            model.graph,
            n,
            cm_extra,
            spec,
            attribute_parallel=cfg.enable_attribute_parallel,
            verbose=cfg.profiling,
            trace=trace,
        )
        extra_best = (
            min(extra, key=lambda r: r.cost.step_time) if extra else None
        )
        if (
            extra_best is not None
            and extra_best.cost.step_time < result.cost
        ):
            # reference prints exactly this at the end of its search
            # (substitution.cc:1909, model.cc:3298)
            print(f"Optimal cost: {extra_best.cost.step_time * 1e3:.6f}")
            if cfg.export_strategy_file:
                from flexflow_tpu.search.strategy_io import (
                    save_search_result,
                )

                save_search_result(
                    extra_best, model.graph, cfg.export_strategy_file
                )
            if trace is not None:
                # the extra-axis gate overrode the engine's pick: the
                # result record must describe the strategy actually
                # lowered (the engine's own record is replaced)
                _record_search_result_trace(trace, extra_best, spec)
            _finish_trace()
            s = result_to_strategy(extra_best, model.graph)
            # the audit (search/audit.py) compares this prediction
            # against the executor's measured step after compile()
            s.predicted_step_time = extra_best.cost.step_time
            return s
        print(f"Optimal cost: {result.cost * 1e3:.6f}")
        if cfg.export_strategy_file:
            unity_mod.save_views(
                result,
                model.graph,
                cfg.export_strategy_file,
                engine=cfg.search_engine,
            )
        _finish_trace()
        s = unity_mod.result_to_strategy(
            result, model.graph, num_devices, engine=cfg.search_engine
        )
        s.predicted_step_time = result.cost
        return s

    result = optimize(
        model.graph,
        n,
        spec,
        budget=max(cfg.search_budget, 1),
        alpha=cfg.search_alpha,
        seed=cfg.seed,
        verbose=cfg.profiling,
        machine_model=mm,
        mixed_precision=cfg.allow_mixed_precision,
        measure=cfg.measure_costs,
        calibration_file=cfg.calibration_file,
        attribute_parallel=cfg.enable_attribute_parallel,
        # mirror the executor's full gate: flag AND an optimizer that
        # implements sparse rows (Executor._sparse_embedding_guids)
        sparse_embedding=sparse_ok,
        trace=trace,
    )
    print(f"[flexflow_tpu] search: best strategy = {result.describe()}")
    if cfg.export_strategy_file:
        from flexflow_tpu.search.strategy_io import save_search_result

        save_search_result(result, model.graph, cfg.export_strategy_file)
    if trace is not None:
        _record_search_result_trace(trace, result, spec)
    _finish_trace()
    s = result_to_strategy(result, model.graph)
    s.predicted_step_time = result.cost.step_time
    return s
