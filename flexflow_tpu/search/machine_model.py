"""Machine models for the strategy search: comm-device chains and network
topology simulation.

Rebuild of the reference's machine-model hierarchy (reference:
src/runtime/machine_model.cc (1287 LoC), simulator.h:203-367;
network simulation src/runtime/network.cc (586 LoC), simulator.h:372-596)
with the comm-device taxonomy swapped from NVLink/PCIe/NIC/membus to the
TPU stack:

  * **ICI** — chip↔chip torus links inside a slice (one device per torus
    axis, so same-axis collectives serialize while cross-axis overlap).
  * **PCIe** — chip↔host, for host-staged transfers and data loading.
  * **DCN** — host↔host NIC across slices.

Three models, mirroring the reference's:

  * `SimpleMachineModel` — two bandwidths: intra-node (ICI) and inter-node
    (DCN) (reference: SimpleMachineModel, simulator.h:203).
  * `EnhancedMachineModel` — parsed from a machine-config file; explicit
    comm devices with latency+bandwidth, per-path device chains, and
    segmented-message pipelining (reference: EnhancedMachineModel +
    machine_config_example; --machine-model-version/-file flags,
    model.cc:3650+).
  * `NetworkedMachineModel` — explicit `ConnectionMatrix` topology over
    nodes and switches with routing strategies and topology generators
    (reference: network.cc; WeightedShortestPathRoutingStrategy etc.).
    The TPU generator of interest is the torus; big-switch / fat-tree /
    fully-connected match the reference's generators for DCN studies.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class CommDevice:
    """One communication resource (reference: CommDevice, simulator.h:133-157
    — {name, device_type, node_id, device_id, latency, bandwidth})."""

    name: str
    kind: str  # "ici" | "pcie" | "dcn" | "link" (networked)
    latency_s: float
    bandwidth_Bps: float

    def time(self, num_bytes: float) -> float:
        return self.latency_s + num_bytes / self.bandwidth_Bps


class MachineModel:
    """Abstract base (reference: MachineModel, simulator.h:203):
    get_comm_path(src, dst) + transfer-time evaluation over the path."""

    def num_chips(self) -> int:
        raise NotImplementedError

    def get_comm_path(self, src_chip: int, dst_chip: int) -> List[CommDevice]:
        raise NotImplementedError

    def transfer_time(self, src_chip: int, dst_chip: int, num_bytes: float) -> float:
        """Un-segmented: sum of device times along the chain."""
        path = self.get_comm_path(src_chip, dst_chip)
        return sum(d.time(num_bytes) for d in path)


@dataclasses.dataclass
class SimpleMachineModel(MachineModel):
    """Intra-node ICI / inter-node DCN, one bandwidth each
    (reference: SimpleMachineModel — intra-node BW / inter-node BW)."""

    num_nodes: int
    chips_per_node: int
    ici_gbps: float = 45.0
    dcn_gbps: float = 25.0
    ici_latency_s: float = 1e-6
    dcn_latency_s: float = 10e-6

    def num_chips(self) -> int:
        return self.num_nodes * self.chips_per_node

    def get_comm_path(self, src_chip: int, dst_chip: int) -> List[CommDevice]:
        if src_chip == dst_chip:
            return []
        same_node = (
            src_chip // self.chips_per_node == dst_chip // self.chips_per_node
        )
        if same_node:
            return [
                CommDevice("ici", "ici", self.ici_latency_s, self.ici_gbps * 1e9)
            ]
        return [
            CommDevice("dcn", "dcn", self.dcn_latency_s, self.dcn_gbps * 1e9)
        ]


class EnhancedMachineModel(MachineModel):
    """Config-file machine model with comm-device chains and segmented
    pipelining (reference: EnhancedMachineModel, machine_model.cc; config
    format modeled on machine_config_example).

    Config format (key = value, '#' comments):

        num_nodes = 2
        chips_per_node = 4
        ici_bandwidth_gbps = 45      # per torus link
        ici_latency_us = 1
        ici_dims = 2                 # torus axes inside a slice
        pcie_bandwidth_gbps = 32
        pcie_latency_us = 2
        dcn_bandwidth_gbps = 25
        dcn_latency_us = 10
        segment_size_mb = 16         # message segmentation unit
        inter_slice = host           # "host" (chip-pcie-dcn-pcie-chip)
                                     # or "direct" (ici-extended slices)
    """

    _KEYS = frozenset(
        {
            "num_nodes",
            "chips_per_node",
            "ici_bandwidth_gbps",
            "ici_latency_us",
            "ici_dims",
            "pcie_bandwidth_gbps",
            "pcie_latency_us",
            "dcn_bandwidth_gbps",
            "dcn_latency_us",
            "segment_size_mb",
            "inter_slice",
        }
    )

    def __init__(self, text: str):
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "=" not in line:
                raise ValueError(f"bad machine-config line: {line!r}")
            k, v = (s.strip() for s in line.split("=", 1))
            if k not in self._KEYS:
                raise ValueError(
                    f"unknown machine-config key {k!r}; known keys: "
                    f"{sorted(self._KEYS)}"
                )
            kv[k] = v

        def f(key, default):
            return float(kv.get(key, default))

        self.num_nodes = int(f("num_nodes", 1))
        self.chips_per_node = int(f("chips_per_node", 4))
        self.ici_dims = int(f("ici_dims", 2))
        self.segment_bytes = int(f("segment_size_mb", 16) * (1 << 20))
        self.inter_slice = kv.get("inter_slice", "host")
        if self.inter_slice not in ("host", "direct"):
            raise ValueError(f"inter_slice must be host|direct, got {self.inter_slice!r}")
        self._ici = CommDevice(
            "ici", "ici", f("ici_latency_us", 1) * 1e-6,
            f("ici_bandwidth_gbps", 45) * 1e9,
        )
        self._pcie = CommDevice(
            "pcie", "pcie", f("pcie_latency_us", 2) * 1e-6,
            f("pcie_bandwidth_gbps", 32) * 1e9,
        )
        self._dcn = CommDevice(
            "dcn", "dcn", f("dcn_latency_us", 10) * 1e-6,
            f("dcn_bandwidth_gbps", 25) * 1e9,
        )

    @classmethod
    def from_file(cls, path: str) -> "EnhancedMachineModel":
        with open(path) as fh:
            return cls(fh.read())

    def num_chips(self) -> int:
        return self.num_nodes * self.chips_per_node

    def get_comm_path(self, src_chip: int, dst_chip: int) -> List[CommDevice]:
        if src_chip == dst_chip:
            return []
        same = src_chip // self.chips_per_node == dst_chip // self.chips_per_node
        if same:
            # intra-slice: worst case crosses every torus axis once, so the
            # path is one ICI device per axis (ici_dims = 1 means a ring)
            return [self._ici] * max(1, self.ici_dims)
        if self.inter_slice == "direct":
            return [self._ici] * max(1, self.ici_dims) * 2
        return [self._pcie, self._dcn, self._pcie]

    def transfer_time(self, src_chip: int, dst_chip: int, num_bytes: float) -> float:
        """Segmented pipelining (reference: EnhancedMachineModel's
        segmented messages): the message is cut into segments that stream
        through the device chain, so total ≈ latency of the whole chain +
        (num_segments - 1 + chain_length) · slowest-segment time."""
        path = self.get_comm_path(src_chip, dst_chip)
        if not path:
            return 0.0
        nseg = max(1, -(-int(num_bytes) // self.segment_bytes))
        seg = num_bytes / nseg
        lat = sum(d.latency_s for d in path)
        slowest = max(seg / d.bandwidth_Bps for d in path)
        return lat + (nseg - 1 + len(path)) * slowest


# -- networked model ----------------------------------------------------------


@dataclasses.dataclass
class ConnectionMatrix:
    """Explicit link topology over num_nodes + num_switches vertices
    (reference: ConnectionMatrix, simulator.h:372+): conn[i][j] = number of
    parallel links i→j (0 = not connected)."""

    num_nodes: int
    num_switches: int
    conn: List[List[int]]

    @property
    def size(self) -> int:
        return self.num_nodes + self.num_switches

    def degree(self, v: int) -> int:
        return sum(1 for x in self.conn[v] if x > 0)


def torus_topology(dims: Sequence[int]) -> ConnectionMatrix:
    """TPU slice ICI torus (the generator the reference lacks; its closest
    is the flat degree-constrained generator, network.cc)."""
    import itertools

    n = 1
    for d in dims:
        n *= d
    coords = list(itertools.product(*(range(d) for d in dims)))
    index = {c: i for i, c in enumerate(coords)}
    conn = [[0] * n for _ in range(n)]
    for c in coords:
        for ax, d in enumerate(dims):
            if d <= 1:
                continue
            nb = list(c)
            nb[ax] = (nb[ax] + 1) % d
            i, j = index[c], index[tuple(nb)]
            if i != j:
                conn[i][j] += 1
                conn[j][i] += 1
    return ConnectionMatrix(n, 0, conn)


def big_switch_topology(num_nodes: int) -> ConnectionMatrix:
    """All nodes hang off one switch (reference: the 'big switch' NVSwitch /
    single-ToR abstraction)."""
    size = num_nodes + 1
    conn = [[0] * size for _ in range(size)]
    sw = num_nodes
    for i in range(num_nodes):
        conn[i][sw] = conn[sw][i] = 1
    return ConnectionMatrix(num_nodes, 1, conn)


def fully_connected_topology(num_nodes: int) -> ConnectionMatrix:
    conn = [
        [1 if i != j else 0 for j in range(num_nodes)] for i in range(num_nodes)
    ]
    return ConnectionMatrix(num_nodes, 0, conn)


def fat_tree_topology(num_nodes: int, pods: int = 2) -> ConnectionMatrix:
    """Two-level leaf/spine tree: num_nodes leaves split over `pods` leaf
    switches, all leaf switches connected to one spine (a simplified
    fat-tree in the spirit of the reference's generators)."""
    pods = max(1, min(pods, num_nodes))
    num_switches = pods + 1
    size = num_nodes + num_switches
    conn = [[0] * size for _ in range(size)]
    spine = num_nodes + pods
    for i in range(num_nodes):
        leaf = num_nodes + (i * pods) // num_nodes
        conn[i][leaf] = conn[leaf][i] = 1
    for p in range(pods):
        leaf = num_nodes + p
        conn[leaf][spine] = conn[spine][leaf] = 1
    return ConnectionMatrix(num_nodes, num_switches, conn)


class RoutingStrategy:
    """reference: routing strategies in network.cc (weighted/shortest-path
    ECMP)."""

    def route(
        self, topo: ConnectionMatrix, src: int, dst: int
    ) -> Optional[List[int]]:
        raise NotImplementedError


class ShortestPathRouting(RoutingStrategy):
    def route(self, topo, src, dst):
        if src == dst:
            return [src]
        prev = {src: None}
        q = [src]
        while q:
            v = q.pop(0)
            for w in range(topo.size):
                if topo.conn[v][w] > 0 and w not in prev:
                    prev[w] = v
                    if w == dst:
                        path = [w]
                        while prev[path[-1]] is not None:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    q.append(w)
        return None


class WeightedShortestPathRouting(RoutingStrategy):
    """Dijkstra with link weight = 1 / multiplicity: prefers fat links
    (reference: WeightedShortestPathRoutingStrategy)."""

    def route(self, topo, src, dst):
        if src == dst:
            return [src]
        dist = {src: 0.0}
        prev: Dict[int, Optional[int]] = {src: None}
        pq = [(0.0, src)]
        while pq:
            d, v = heapq.heappop(pq)
            if v == dst:
                path = [v]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            if d > dist.get(v, float("inf")):
                continue
            for w in range(topo.size):
                m = topo.conn[v][w]
                if m > 0:
                    nd = d + 1.0 / m
                    if nd < dist.get(w, float("inf")):
                        dist[w] = nd
                        prev[w] = v
                        heapq.heappush(pq, (nd, w))
        return None


class NetworkedMachineModel(MachineModel):
    """Topology-aware model: chips map onto topology nodes; transfer time
    routes through the ConnectionMatrix (reference: NetworkedMachineModel,
    simulator.h:372-596 + network.cc)."""

    def __init__(
        self,
        num_nodes: int,
        chips_per_node: int,
        topology: ConnectionMatrix,
        link_gbps: float = 25.0,
        link_latency_s: float = 5e-6,
        intra_node_gbps: float = 45.0,
        routing: Optional[RoutingStrategy] = None,
    ):
        if topology.num_nodes != num_nodes:
            raise ValueError(
                f"topology has {topology.num_nodes} nodes, expected {num_nodes}"
            )
        self.num_nodes = num_nodes
        self.chips_per_node = chips_per_node
        self.topology = topology
        self.link_gbps = link_gbps
        self.link_latency_s = link_latency_s
        self.intra_node_gbps = intra_node_gbps
        self.routing = routing or WeightedShortestPathRouting()
        self._path_cache: Dict[Tuple[int, int], Optional[List[int]]] = {}
        self._device_cache: Dict[Tuple[int, int], List[CommDevice]] = {}
        self._ici_dev = CommDevice("ici", "ici", 1e-6, intra_node_gbps * 1e9)

    def num_chips(self) -> int:
        return self.num_nodes * self.chips_per_node

    def _node_route(self, a: int, b: int) -> Optional[List[int]]:
        key = (a, b)
        if key not in self._path_cache:
            self._path_cache[key] = self.routing.route(self.topology, a, b)
        return self._path_cache[key]

    def get_comm_path(self, src_chip: int, dst_chip: int) -> List[CommDevice]:
        if src_chip == dst_chip:
            return []
        a = src_chip // self.chips_per_node
        b = dst_chip // self.chips_per_node
        if a == b:
            return [self._ici_dev]
        key = (a, b)
        cached = self._device_cache.get(key)
        if cached is not None:
            return cached
        route = self._node_route(a, b)
        if route is None:
            raise ValueError(f"no route between nodes {a} and {b}")
        devices = []
        for u, v in zip(route, route[1:]):
            mult = max(1, self.topology.conn[u][v])
            devices.append(
                CommDevice(
                    f"link{u}-{v}",
                    "link",
                    self.link_latency_s,
                    self.link_gbps * 1e9 * mult,
                )
            )
        self._device_cache[key] = devices
        return devices


def build_machine_model(config, spec) -> Optional[MachineModel]:
    """--machine-model-version dispatch (reference: graph.cc:1566-1581):
    0 = Simple (None here: the CostModel's built-in ring formulas),
    1 = Enhanced from --machine-model-file,
    2 = Networked torus of the slice."""
    version = getattr(config, "machine_model_version", 0)
    if version not in (0, 1, 2):
        raise ValueError(
            f"unknown --machine-model-version {version}; expected 0 | 1 | 2"
        )
    if version == 1:
        if not getattr(config, "machine_model_file", ""):
            raise ValueError("--machine-model-version 1 needs --machine-model-file")
        return EnhancedMachineModel.from_file(config.machine_model_file)
    if version == 2:
        topo = torus_topology((spec.num_nodes,)) if spec.num_nodes > 1 else (
            fully_connected_topology(1)
        )
        return NetworkedMachineModel(
            spec.num_nodes,
            spec.chips_per_node,
            topo,
            link_gbps=spec.dcn_bandwidth_gbps,
            intra_node_gbps=spec.ici_gbps,
        )
    return None
