"""Auto-parallelization search (SURVEY §2.5 rebuild).

cost_model — roofline op costs + ICI collective formulas
simulator  — per-candidate step-time estimation
rewrites   — TP substitution sites (Megatron linear pairs, attention heads)
auto       — mesh × site search (greedy + MCMC under --budget)
strategy_io — JSON --export-strategy / --import-strategy
"""

from flexflow_tpu.search.auto import optimize, result_to_strategy, search_strategy
from flexflow_tpu.search.cost_model import CostModel, OpCost
from flexflow_tpu.search.rewrites import find_tp_sites
from flexflow_tpu.search.simulator import GraphCost, estimate_graph_cost
from flexflow_tpu.search.strategy_io import load_strategy, save_search_result

__all__ = [
    "optimize",
    "result_to_strategy",
    "search_strategy",
    "CostModel",
    "OpCost",
    "find_tp_sites",
    "GraphCost",
    "estimate_graph_cost",
    "load_strategy",
    "save_search_result",
]
