"""explain_strategy: why the winning strategy won.

``UnityResult.describe()`` compressed a whole search into one line —
a cost and a grid histogram. This module is its upgrade: given the
search-trace artifact (`telemetry.search_trace.SearchTrace`, exported
via ``--search-trace`` / built in-process by ``--explain``), it
reconstructs the decision:

* the run header (engine, seed, budget, temperature schedule, machine)
  — everything needed to reproduce the search;
* the winning total, rebuilt EXACTLY from the per-op breakdown: the
  result record stores each op's ``(op_cost, xfer_cost)`` plus a
  ``residual`` defined as ``total - sum(breakdown in order)``, so
  summing in the same order and adding the residual inverts the
  subtraction to within a float ulp (asserted at 1e-9 by
  tests/test_search_trace.py on both the native and python DP paths);
* where the time goes — top ops by cost share, per-family and
  per-(dp, ch)-grid totals, transfer vs compute split;
* how hard the search worked — candidates considered, accept/reject
  tallies (MCMC), measured-LUT hits vs analytic roofline estimates,
  phase durations;
* the near misses — the best rejected proposals, the margin the winner
  won by over the runner-up whole-config candidates.

CLI::

    python -m flexflow_tpu.search.explain TRACE.jsonl [STRATEGY.json ...]
        [--no-validate]

Accepts search-trace JSONL files and exported strategy files
(``--export-strategy``: unity per-op view docs and mesh SearchResult
docs) in any mix; traces are schema-validated first (exit 2 on a
violation — a corrupt artifact must not explain anything).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Union

__all__ = ["ExplainReport", "explain_strategy", "load_search_trace", "main"]


def load_search_trace(path: str) -> List[dict]:
    """Rows of an exported search-trace JSONL file."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


@dataclasses.dataclass
class ExplainReport:
    """The reconstructed decision record of one strategy search."""

    engine: str
    header: dict
    result: dict
    ops: List[dict]
    total_cost: float           # the winner's recorded total (seconds)
    reconstructed_total: float  # sum(breakdown in order) + residual
    residual: float
    candidates: List[dict]
    phases: List[dict]
    events: List[dict]

    # -- derived views --------------------------------------------------------

    def per_family(self) -> Dict[str, float]:
        """op_cost + xfer_cost grouped by cost-model family (falls back
        to the op type when the family map doesn't know the op)."""
        from flexflow_tpu.core.types import OperatorType
        from flexflow_tpu.search.cost_model import op_family

        out: Dict[str, float] = {}
        for entry in self.ops:
            fam = None
            op = entry.get("op")
            if op is not None and hasattr(OperatorType, op):
                fam = op_family(getattr(OperatorType, op))
            key = fam or (op or "other").lower()
            out[key] = out.get(key, 0.0) + (
                entry.get("op_cost", 0.0) + entry.get("xfer_cost", 0.0)
            )
        return out

    def per_grid(self) -> Dict[str, float]:
        """Cost share per (dp, ch) factorization."""
        out: Dict[str, float] = {}
        for entry in self.ops:
            key = f"dp{entry.get('dp', '?')}xch{entry.get('ch', '?')}"
            out[key] = out.get(key, 0.0) + (
                entry.get("op_cost", 0.0) + entry.get("xfer_cost", 0.0)
            )
        return out

    def top_ops(self, k: int = 5) -> List[dict]:
        return sorted(
            self.ops,
            key=lambda e: e.get("op_cost", 0.0) + e.get("xfer_cost", 0.0),
            reverse=True,
        )[:k]

    def near_misses(self, k: int = 3) -> List[dict]:
        """The best REJECTED proposals — what the search almost took
        (smallest positive delta), the TASO-style justification that
        the winner beat concrete alternatives."""
        rejected = [
            c
            for c in self.candidates
            if c.get("accepted") is False and c.get("delta") is not None
        ]
        return sorted(rejected, key=lambda c: c["delta"])[:k]

    def runner_up(self) -> Optional[dict]:
        """The cheapest whole-config candidate that is NOT the winner
        (graph_cost / extra_axis records carry step_time)."""
        configs = [
            c
            for c in self.candidates
            if c.get("step_time") is not None
            and c.get("feasible", True)
            and c.get("step_time") > self.total_cost * (1 + 1e-12)
        ]
        return min(configs, key=lambda c: c["step_time"]) if configs else None

    # -- rendering ------------------------------------------------------------

    def text(self) -> str:
        h = self.header
        r = self.result
        ms = self.total_cost * 1e3
        lines = [
            f"strategy explain — engine {self.engine or '?'}, "
            f"simulated step {ms:.3f} ms",
        ]
        meta = []
        for key in ("seed", "budget", "alpha"):
            if h.get(key) is not None:
                meta.append(f"{key}={h[key]}")
        temp = h.get("temperature")
        if isinstance(temp, dict):
            meta.append(
                f"temperature={temp.get('kind', '?')}"
                f"(accept {temp.get('acceptance', '?')}, "
                f"reset every {temp.get('reset_every', '?')})"
            )
        machine = h.get("machine")
        if isinstance(machine, dict):
            meta.append(
                f"machine={machine.get('num_nodes', '?')}x"
                f"{machine.get('chips_per_node', '?')} "
                f"{machine.get('chip', '')}"
            )
        if meta:
            lines.append("  run: " + ", ".join(meta))
        # live tallies over the candidate rows (the result record's
        # snapshot can predate late extra-axis candidates)
        cands = self.candidates
        lines.append(
            "  search effort: "
            f"{len(cands)} candidates "
            f"({sum(1 for c in cands if c.get('accepted') is True)} "
            "accepted / "
            f"{sum(1 for c in cands if c.get('accepted') is False)} "
            "rejected), "
            f"{sum(1 for c in cands if c.get('source') == 'measured')} "
            "measured-LUT leaf costs vs "
            f"{sum(1 for c in cands if c.get('source') == 'analytic')} "
            "analytic"
        )
        if r.get("path") or r.get("kind"):
            lines.append(
                f"  winner: {r.get('name', '(per-op view map)')} "
                f"[{r.get('path') or r.get('kind')}]"
            )
        if self.ops:
            lines.append(
                f"  cost reconstruction: {len(self.ops)} ops sum to "
                f"{(self.reconstructed_total - self.residual) * 1e3:.3f} ms "
                f"+ residual {self.residual * 1e3:.3f} ms "
                "(DP concurrency / dispatch floor) "
                f"= {self.reconstructed_total * 1e3:.3f} ms"
            )
            grids = self.per_grid()
            lines.append(
                "  (dp, ch) grids: "
                + ", ".join(
                    f"{g}: {v * 1e3:.3f} ms"
                    for g, v in sorted(
                        grids.items(), key=lambda kv: -kv[1]
                    )
                )
            )
            fams = self.per_family()
            lines.append(
                "  per family: "
                + ", ".join(
                    f"{f}: {v * 1e3:.3f} ms"
                    for f, v in sorted(
                        fams.items(), key=lambda kv: -kv[1]
                    )
                )
            )
            lines.append("  top ops:")
            denom = max(self.reconstructed_total, 1e-30)
            for e in self.top_ops():
                c = e.get("op_cost", 0.0) + e.get("xfer_cost", 0.0)
                lines.append(
                    f"    {e.get('name', '?'):<28} "
                    f"dp{e.get('dp', '?')}xch{e.get('ch', '?')}  "
                    f"{c * 1e3:9.3f} ms ({100 * c / denom:5.1f}%)"
                    + (
                        f"  [xfer {e['xfer_cost'] * 1e3:.3f} ms]"
                        if e.get("xfer_cost", 0.0) > 0
                        else ""
                    )
                )
        ru = self.runner_up()
        if ru is not None:
            lines.append(
                f"  runner-up config: {ru.get('name', ru.get('kind', '?'))} "
                f"at {ru['step_time'] * 1e3:.3f} ms "
                f"(+{(ru['step_time'] - self.total_cost) * 1e3:.3f} ms)"
            )
        for c in self.near_misses():
            lines.append(
                "  near miss (rejected): "
                f"{c.get('kind', '?')} on guid {c.get('guid', '?')} "
                f"delta +{c.get('delta', 0.0) * 1e3:.4f} ms "
                f"at iter {c.get('iteration', '?')}"
            )
        if self.phases:
            lines.append(
                "  phases: "
                + ", ".join(
                    f"{p['name']} "
                    f"{(p['t_end_s'] - p['t_start_s']) * 1e3:.1f} ms"
                    for p in self.phases
                )
            )
        return "\n".join(lines)


def explain_strategy(
    source: Union[str, Sequence[dict], "object"],
) -> ExplainReport:
    """Build the explain report from a search trace: a JSONL path, the
    row list, or a live SearchTrace. The reconstructed total is the
    in-order breakdown sum plus the recorded residual — equal to the
    winning result's total cost (the exactness contract the tests hold
    at 1e-9)."""
    if hasattr(source, "rows"):
        rows = source.rows()
    elif isinstance(source, str):
        rows = load_search_trace(source)
    else:
        rows = list(source)
    header: dict = {}
    result: Optional[dict] = None
    candidates: List[dict] = []
    phases: List[dict] = []
    events: List[dict] = []
    for row in rows:
        t = row.get("type")
        if t == "header":
            header = row
        elif t == "candidate":
            candidates.append(row)
        elif t == "phase":
            phases.append(row)
        elif t == "event":
            events.append(row)
        elif t == "result":
            result = row
    if result is None:
        raise ValueError(
            "search trace has no result record — the search did not "
            "finish (or the artifact was truncated)"
        )
    ops = list(result.get("ops", ()))
    residual = float(result.get("residual", 0.0))
    listed = 0.0
    for entry in ops:  # SAME order as the recorder summed in
        listed += entry.get("op_cost", 0.0) + entry.get("xfer_cost", 0.0)
    return ExplainReport(
        engine=result.get("engine") or header.get("engine", ""),
        header=header,
        result=result,
        ops=ops,
        total_cost=float(result["total_cost"]),
        reconstructed_total=listed + residual,
        residual=residual,
        candidates=candidates,
        phases=phases,
        events=events,
    )


# -- exported strategy files ---------------------------------------------------


def describe_strategy_file(path: str) -> str:
    """Human-readable summary of an exported strategy file: the unity
    per-op view doc (unity.save_views) or the mesh SearchResult doc
    (strategy_io.save_search_result)."""
    with open(path) as f:
        doc = json.load(f)
    lines = [f"strategy file {path}:"]
    if "ops" in doc:  # unity per-op view map
        lines.append(
            f"  engine {doc.get('engine', '?')}, simulated step "
            f"{doc.get('simulated_step_ms', float('nan')):.3f} ms, "
            f"{len(doc['ops'])} op views"
        )
        grids: Dict[str, int] = {}
        for spec in doc["ops"].values():
            key = f"dp{spec.get('dp', '?')}xch{spec.get('ch', '?')}"
            grids[key] = grids.get(key, 0) + 1
        lines.append(
            "  (dp, ch) grids: "
            + ", ".join(f"{k}: {v} ops" for k, v in sorted(grids.items()))
        )
        for name, spec in list(sorted(doc["ops"].items()))[:8]:
            lines.append(
                f"    {name:<28} dp{spec.get('dp')}xch{spec.get('ch')} "
                f"view start={spec.get('start_device_id')} "
                f"dims={spec.get('dims')}"
            )
        if len(doc["ops"]) > 8:
            lines.append(f"    ... {len(doc['ops']) - 8} more")
    else:  # mesh SearchResult doc
        lines.append(
            f"  kind {doc.get('kind', 'tp')}: mesh(data={doc.get('dp')}, "
            f"model={doc.get('tp')}), {len(doc.get('sites', []))} sites "
            f"on, simulated step "
            f"{doc.get('simulated_step_ms', float('nan')):.3f} ms"
        )
        for site in doc.get("sites", [])[:8]:
            lines.append(
                f"    site {site.get('kind')}: "
                f"{', '.join(site.get('names', []))}"
            )
    return "\n".join(lines)


def _is_trace_file(path: str) -> bool:
    with open(path) as f:
        first = f.readline().strip()
    if not first:
        return False
    try:
        row = json.loads(first)
    except ValueError:
        return False
    return isinstance(row, dict) and "type" in row


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.search.explain",
        description="Explain a strategy search from its exported "
        "artifacts (search-trace JSONL and/or strategy JSON files).",
    )
    parser.add_argument(
        "files", nargs="+",
        help="search-trace .jsonl exports (--search-trace) and/or "
        "strategy .json exports (--export-strategy)",
    )
    parser.add_argument(
        "--no-validate", action="store_true",
        help="skip schema validation of trace files",
    )
    args = parser.parse_args(argv)
    rc = 0
    for path in args.files:
        if _is_trace_file(path):
            if not args.no_validate:
                from flexflow_tpu.telemetry.validate import (
                    validate_search_trace_file,
                )

                errs = validate_search_trace_file(path, errors="list")
                if errs:
                    print(f"{path}: INVALID search trace:")
                    for e in errs[:10]:
                        print(f"  {e}")
                    rc = 2
                    continue
            try:
                report = explain_strategy(path)
            except ValueError as e:
                print(f"{path}: {e}")
                rc = 2
                continue
            print(report.text())
        else:
            print(describe_strategy_file(path))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
