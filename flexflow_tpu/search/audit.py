"""Predicted-vs-measured cost-model audit.

The paper's whole premise is a simulator accurate enough to rank
strategies (measure, then decide) — but until now nothing ever checked
whether the strategy the search picked was actually fast once executed:
`estimate_graph_cost` predicted a step time at compile, the executor
ran, and the two numbers never met. This module closes that loop:

* **predicted** — the searcher's own `GraphCost` for the COMPILED
  (annotated) graph, re-derived with the same CostModel basis the
  search used, with the per-node breakdown exported by
  `estimate_graph_cost(export=...)` and grouped by cost-model family
  (`cost_model.op_family`);
* **measured** — the real executor: whole-step wall clock via the
  bench methodology (`utils.benchmark.measure_train_step`, on-device
  scan differencing) and per-op forward times via
  `utils.profiling.profile_operators` (isolated-kernel basis — the
  same structural bias the cost model documents, so family ratios are
  compared forward-vs-forward on that shared basis);
* **exported** — `cost_model_error_ratio{family=...}` gauges
  (predicted / measured; 1.0 = calibrated, >1 over-prediction) in a
  MetricsRegistry, plus an ``audit`` entry fed back through the
  existing `update_calibration_doc` read-merge-write path so repeated
  runs accumulate the residual history next to the measured-kernel
  table they judge. `apply_family_scale=True` additionally merges the
  measured family residuals into the ``family_scale`` correction the
  measured-mode search divides out — the full calibration loop
  (calibrate.py --fit-family remains the precision tool; this is the
  in-situ coarse pass).

Entry points: `audit_cost_model(model, ...)` after `compile()` (also
surfaced as `FFModel.audit_cost_model`), and `bench.py --audit` which
writes BENCH_COST_AUDIT.json in CI.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = ["CostAuditResult", "FamilyAudit", "audit_cost_model"]


@dataclasses.dataclass
class FamilyAudit:
    """One op family's predicted-vs-measured forward-time comparison
    (isolated-kernel basis on the measured side)."""

    family: str
    predicted_s: float
    measured_s: float

    @property
    def error_ratio(self) -> float:
        return (
            self.predicted_s / self.measured_s
            if self.measured_s > 0
            else float("inf")
        )


@dataclasses.dataclass
class CostAuditResult:
    """The full audit: whole-step prediction vs wall clock, per-family
    forward residuals, and the search's own predicted step time when a
    searched strategy produced one."""

    predicted_step_s: float      # estimate_graph_cost on the compiled graph
    measured_step_s: float       # executor wall clock (scan differencing)
    families: Dict[str, FamilyAudit]
    searched_step_s: Optional[float] = None  # strategy.predicted_step_time
    node_costs: List[dict] = dataclasses.field(default_factory=list)

    @property
    def step_error_ratio(self) -> float:
        return (
            self.predicted_step_s / self.measured_step_s
            if self.measured_step_s > 0
            else float("inf")
        )

    def describe(self) -> str:
        lines = [
            "cost-model audit: predicted "
            f"{self.predicted_step_s * 1e3:.3f} ms vs measured "
            f"{self.measured_step_s * 1e3:.3f} ms per step "
            f"(ratio {self.step_error_ratio:.3f})",
        ]
        if self.searched_step_s is not None:
            lines.append(
                f"  search predicted {self.searched_step_s * 1e3:.3f} ms "
                "for the lowered strategy"
            )
        for fam in sorted(
            self.families.values(), key=lambda f: -f.measured_s
        ):
            lines.append(
                f"  {fam.family:<10} predicted {fam.predicted_s * 1e3:8.3f}"
                f" ms, profiled {fam.measured_s * 1e3:8.3f} ms "
                f"(ratio {fam.error_ratio:.3f})"
            )
        return "\n".join(lines)

    def to_doc(self) -> dict:
        """The JSON shape fed back through update_calibration_doc and
        written by bench.py --audit."""
        return {
            "predicted_step_ms": self.predicted_step_s * 1e3,
            "measured_step_ms": self.measured_step_s * 1e3,
            "step_error_ratio": self.step_error_ratio,
            "searched_step_ms": (
                self.searched_step_s * 1e3
                if self.searched_step_s is not None
                else None
            ),
            "families": {
                f.family: {
                    "predicted_ms": f.predicted_s * 1e3,
                    "measured_ms": f.measured_s * 1e3,
                    "error_ratio": f.error_ratio,
                }
                for f in self.families.values()
            },
        }


def _zero_batch(model) -> dict:
    """Zero-filled example batch on the executor's input shapes (the
    init_operators recipe) — the audit must not require real data."""
    import numpy as np

    return {
        name: np.zeros(
            tuple(d.size for d in shape.dims if not d.is_replica_dim),
            shape.dtype.to_jnp(),
        )
        for name, shape in model.executor.input_shapes().items()
    }


def audit_cost_model(
    model,
    batch=None,
    reps: int = 4,
    profile_iters: int = 3,
    registry=None,
    calibration_file: Optional[str] = None,
    apply_family_scale: bool = False,
) -> CostAuditResult:
    """Run the predicted-vs-measured audit on a compiled model.

    batch: host arrays keyed like fit()'s (label included); a
    zero-filled batch on the executor's input shapes is synthesized
    when omitted. registry: a telemetry.MetricsRegistry to export
    `cost_model_error_ratio{family=...}` gauges into (the model's
    attached fit-telemetry registry is used when one exists).
    calibration_file: defaults to the config's --calibration-file;
    pass "" to skip the write-back."""
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import build_machine_model
    from flexflow_tpu.search.simulator import estimate_graph_cost
    from flexflow_tpu.utils.benchmark import measure_train_step
    from flexflow_tpu.utils.profiling import profile_operators

    if model.executor is None:
        raise RuntimeError("call compile() before audit_cost_model()")
    cfg = model.config
    n = int(model.executor.mesh.devices.size)
    spec = MachineSpec(
        num_nodes=max(1, cfg.num_nodes),
        chips_per_node=max(1, n // max(1, cfg.num_nodes)),
        chip=cfg.chip,
    )
    sparse_ok = cfg.sparse_embedding_update and (
        model.optimizer is None or model.optimizer.supports_sparse()
    )
    cm = CostModel(
        spec,
        measure=cfg.measure_costs,
        machine_model=build_machine_model(cfg, spec),
        mixed_precision=cfg.allow_mixed_precision,
        calibration_file=cfg.calibration_file,
        sparse_embedding=sparse_ok,
    )
    # predicted: the SAME annotated graph the executor lowered, priced
    # on the same basis the search ranks candidates with
    export: dict = {}
    predicted = estimate_graph_cost(
        model.graph,
        cm,
        model.strategy.mesh_config.axis_sizes,
        export=export,
    )
    node_costs = export.get("node_costs", [])
    pred_fwd_by_family: Dict[str, float] = {}
    for entry in node_costs:
        fam = entry["family"]
        pred_fwd_by_family[fam] = (
            pred_fwd_by_family.get(fam, 0.0) + entry["forward"]
        )

    # measured: whole-step wall clock + per-op isolated forward profile
    host_batch = batch if batch is not None else _zero_batch(model)
    sharded = model.executor.shard_batch(host_batch)
    measured_step = measure_train_step(model, sharded, reps=reps)
    prof_rows = profile_operators(
        model, host_batch, iters=profile_iters, verbose=False
    )
    name_to_family: Dict[str, str] = {}
    from flexflow_tpu.search.cost_model import op_family

    for node in model.graph.nodes.values():
        name_to_family[node.name] = op_family(node.op_type) or "other"
    meas_fwd_by_family: Dict[str, float] = {}
    for name, seconds in prof_rows:
        fam = name_to_family.get(name, "other")
        meas_fwd_by_family[fam] = meas_fwd_by_family.get(fam, 0.0) + seconds

    families = {
        fam: FamilyAudit(
            fam,
            pred_fwd_by_family.get(fam, 0.0),
            meas_fwd_by_family.get(fam, 0.0),
        )
        for fam in sorted(
            set(pred_fwd_by_family) | set(meas_fwd_by_family)
        )
    }
    result = CostAuditResult(
        predicted_step_s=predicted.step_time,
        measured_step_s=measured_step,
        families=families,
        searched_step_s=getattr(
            model.strategy, "predicted_step_time", None
        ),
        node_costs=node_costs,
    )

    # export gauges: the series the ROADMAP's calibration dashboards
    # scrape — one per family plus the whole-step ratio under _step
    if registry is None:
        tele = getattr(model, "_telemetry", None)
        registry = tele.registry if tele is not None else None
    if registry is not None:
        for fam in families.values():
            if fam.measured_s > 0:
                registry.gauge(
                    "cost_model_error_ratio",
                    help="predicted / measured time (1.0 = calibrated)",
                    labels={"family": fam.family},
                ).set(fam.error_ratio)
        if result.measured_step_s > 0:
            registry.gauge(
                "cost_model_error_ratio",
                help="predicted / measured time (1.0 = calibrated)",
                labels={"family": "_step"},
            ).set(result.step_error_ratio)

    # feed the residuals back through the ONE calibration write path
    if calibration_file is None:
        calibration_file = cfg.calibration_file
    if calibration_file:
        from flexflow_tpu.search.cost_model import update_calibration_doc

        updates: dict = {"audit": result.to_doc()}
        if apply_family_scale:
            # family_scale divides measured costs (corrected = raw /
            # scale), so the residual that would make predicted match
            # measured is predicted/measured on the shared forward
            # basis — merged per family, never wiping siblings
            updates["family_scale"] = {
                f.family: f.error_ratio
                for f in families.values()
                if f.measured_s > 0 and f.predicted_s > 0
            }
        update_calibration_doc(
            calibration_file, updates, chip=cfg.chip
        )
    return result
