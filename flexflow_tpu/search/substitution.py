"""TASO-style graph-substitution engine with JSON rule loading.

TPU rebuild of the reference's substitution subsystem (reference:
src/runtime/substitution.cc — `GraphXfer` with pattern (`srcOps`) and
replacement (`dstOps`) `OpX` nodes, backtracking match, `create_new_graph`;
include/flexflow/substitution_loader.h + src/runtime/substitution_loader.cc —
JSON rule files like substitutions/graph_subst_3_v2.json, loaded via
`create_xfers` at substitution.cc:1587-1664).

A rule is a pair of small op graphs over shared symbolic input tensors:

    srcOps  — the pattern to match in the PCG (with parameter constraints),
    dstOps  — the replacement subgraph, built over the same symbolic inputs,
    mapped_outputs — which src outputs are re-routed to which dst outputs.

Loading semantics kept from the reference (create_xfer,
substitution.cc:1587-1614):

  * `input` entries with opId >= 0 refer to output tsId of the rule-op at
    that index; opId < 0 names an external input, shared between src and dst
    sides by (opId, tsId).
  * generated rules always carry `PM_PARALLEL_DEGREE == 2`; the loader
    generalizes this to the requested `parallel_degree`
    (reference: "Assume the generator only consider a parallel degree of 2",
    substitution.cc:1486-1488).
  * a dst compute op (Linear/Concat/…) inherits its full parameters from the
    unique src op of the same type (reference: find_opx_with_type,
    substitution.cc:1520-1531).

Dim-numbering translation: rule files index tensor dims in the reference's
Legion order (dim 0 = innermost/fastest-varying; the replica dim sits past
the outermost dim). Our shapes are numpy-ordered with replica dims
prepended, so ff-dim d on a tensor with n non-replica dims maps to numpy
axis (n-1-d), and d == n denotes the replica dim.

PM_ACTI uses the TASO generator's activation encoding (0 = none, 2 = relu),
not ffconst's AC_MODE_* values; we decode accordingly (the reference passes
the raw value through, substitution.cc:1511-1513, so its generated linear
rules compare 0/2 against AC_MODE_* and can never fire — a latent bug we do
not reproduce).
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import json
import os
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from flexflow_tpu.core.pcg import PCGGraph, PCGNode, TensorRef
from flexflow_tpu.core.types import ActiMode, OperatorType

# ---------------------------------------------------------------------------
# Pattern IR: TensorX / OpX / GraphXfer
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TensorX:
    """A symbolic tensor in a rule: either output `idx` of rule op `op`
    (internal), or external input `idx` when op is None."""

    op: Optional["OpX"]
    idx: int

    @property
    def is_external(self) -> bool:
        return self.op is None


@dataclasses.dataclass(frozen=True)
class Constraint:
    """An equality constraint on a matched op's parameter, in the rule-file
    vocabulary (PM_* keys; reference: OpX::add_pm_constraint)."""

    key: str
    value: int


class OpX:
    """One pattern/replacement operator (reference: OpX, substitution.h)."""

    def __init__(
        self,
        op_type: OperatorType,
        inputs: Sequence[TensorX],
        constraints: Sequence[Constraint] = (),
        num_outputs: int = 1,
    ):
        self.op_type = op_type
        self.inputs = tuple(inputs)
        self.constraints = tuple(constraints)
        self.num_outputs = num_outputs

    def out(self, idx: int = 0) -> TensorX:
        return TensorX(self, idx)

    def constraint_value(self, key: str) -> Optional[int]:
        for c in self.constraints:
            if c.key == key:
                return c.value
        return None

    def __repr__(self):
        return f"OpX({self.op_type.name}, {len(self.inputs)} in)"


class GraphXfer:
    """A substitution rule: match src_ops in a PCG, replace with dst_ops."""

    def __init__(
        self,
        name: str,
        src_ops: Sequence[OpX],
        dst_ops: Sequence[OpX],
        mapped_outputs: Sequence[Tuple[TensorX, TensorX]],
        model_axis: int = 1,
    ):
        self.name = name
        self.src_ops = list(src_ops)
        self.dst_ops = list(dst_ops)
        self.mapped_outputs = list(mapped_outputs)
        self.model_axis = model_axis

    # -- matching -----------------------------------------------------------

    def find_matches(
        self, graph: PCGGraph, limit: int = 64
    ) -> List[Tuple[Dict[OpX, int], Dict[TensorX, TensorRef]]]:
        """Backtracking search for pattern embeddings
        (reference: GraphXfer::run's DFS over srcOps).

        Returns up to `limit` (op mapping, external-tensor binding) pairs.
        """
        matches: List[Tuple[Dict[OpX, int], Dict[TensorX, TensorRef]]] = []
        mapping: Dict[OpX, int] = {}
        binding: Dict[TensorX, TensorRef] = {}

        candidates_by_type: Dict[OperatorType, List[int]] = {}
        for guid in graph.topo_order():
            candidates_by_type.setdefault(
                graph.nodes[guid].op_type, []
            ).append(guid)

        def try_op(i: int):
            if len(matches) >= limit:
                return
            if i == len(self.src_ops):
                if self._check_match_closure(graph, mapping):
                    matches.append((dict(mapping), dict(binding)))
                return
            opx = self.src_ops[i]
            for guid in candidates_by_type.get(opx.op_type, ()):
                if guid in mapping.values():
                    continue
                node = graph.nodes[guid]
                if len(node.inputs) != len(opx.inputs):
                    continue
                if not self._constraints_ok(graph, node, opx):
                    continue
                new_bindings = []
                ok = True
                for tx, ref in zip(opx.inputs, node.inputs):
                    if tx.is_external:
                        if tx in binding:
                            if binding[tx] != ref:
                                ok = False
                                break
                        else:
                            binding[tx] = ref
                            new_bindings.append(tx)
                    else:
                        src_opx = tx.op
                        if src_opx not in mapping:
                            # pattern inputs always reference earlier ops
                            ok = False
                            break
                        if ref != TensorRef(mapping[src_opx], tx.idx):
                            ok = False
                            break
                if ok:
                    mapping[opx] = guid
                    try_op(i + 1)
                    del mapping[opx]
                for tx in new_bindings:
                    del binding[tx]
                if len(matches) >= limit:
                    return

        try_op(0)
        return matches

    def _check_match_closure(
        self, graph: PCGGraph, mapping: Dict[OpX, int]
    ) -> bool:
        """Every output of a matched node consumed outside the match must be
        a mapped output; otherwise the rewrite would orphan a live tensor
        (reference: create_new_graph's external-edge check)."""
        matched = set(mapping.values())
        mapped_src = set()
        for src_tx, _ in self.mapped_outputs:
            mapped_src.add((mapping[src_tx.op], src_tx.idx))
        for opx, guid in mapping.items():
            for c in graph.consumers(guid):
                if c in matched:
                    continue
                consumer = graph.nodes[c]
                for ref in consumer.inputs:
                    if ref.guid == guid and (guid, ref.out_idx) not in mapped_src:
                        return False
        return True

    def _constraints_ok(
        self, graph: PCGGraph, node: PCGNode, opx: OpX
    ) -> bool:
        for c in opx.constraints:
            actual = _node_pm(graph, node, c.key)
            if actual is None or actual != c.value:
                return False
        return True

    # -- application ---------------------------------------------------------

    def apply(
        self,
        graph: PCGGraph,
        mapping: Dict[OpX, int],
        binding: Dict[TensorX, TensorRef],
    ) -> Tuple[PCGGraph, Dict[TensorRef, TensorRef]]:
        """Build the rewritten graph (reference: GraphXfer::create_new_graph).

        Returns (new graph, {old ref → new ref} for mapped outputs). Raises
        ValueError if the result is invalid (cycle / shape mismatch) —
        callers treat that as "rule does not apply here".
        """
        from flexflow_tpu.ops.registry import infer_shapes
        from flexflow_tpu.runtime.executor import propagate_shapes

        g = graph.copy()
        dst_nodes: Dict[OpX, PCGNode] = {}

        def resolve(tx: TensorX) -> TensorRef:
            if tx.is_external:
                return binding[tx]
            if tx.op in dst_nodes:
                return TensorRef(dst_nodes[tx.op].guid, tx.idx)
            # a dst input referencing a src op's output directly
            if tx.op in mapping:
                return TensorRef(mapping[tx.op], tx.idx)
            raise ValueError("unresolvable rule tensor")

        for opx in self.dst_ops:
            in_refs = [resolve(tx) for tx in opx.inputs]
            params = self._dst_params(g, opx, mapping, graph, in_refs)
            # infer real output shapes immediately so later dst ops in the
            # chain translate ff dims against correct ranks (a placeholder
            # here would feed _ff_dim_to_axis the pre-op shape)
            in_shapes = [g.shape_of(r) for r in in_refs]
            outs, weights = infer_shapes(opx.op_type, in_shapes, params)
            node = g.add_node(
                opx.op_type,
                f"{self.name}.{opx.op_type.name.lower()}",
                in_refs,
                params,
                outs,
                weights,
            )
            dst_nodes[opx] = node

        ref_map: Dict[TensorRef, TensorRef] = {}
        matched = set(mapping.values())
        for src_tx, dst_tx in self.mapped_outputs:
            old = TensorRef(mapping[src_tx.op], src_tx.idx)
            new = TensorRef(dst_nodes[dst_tx.op].guid, dst_tx.idx)
            ref_map[old] = new
            for c in list(g.consumers(old.guid)):
                if c not in matched and c not in {
                    n.guid for n in dst_nodes.values()
                }:
                    g.replace_input(c, old, new)

        for guid in matched:
            g.remove_node(guid)

        propagate_shapes(g)  # validates: raises on cycle or shape break
        return g, ref_map

    def _dst_params(
        self,
        g: PCGGraph,
        opx: OpX,
        mapping: Dict[OpX, int],
        old_graph: PCGGraph,
        in_refs: Sequence[TensorRef],
    ) -> Dict[str, object]:
        """Parameters for an instantiated dst op: parallel ops from the rule's
        constraints; compute ops copied from the unique matched src op of the
        same type (reference: find_opx_with_type), overlaid with any
        constraint-pinned values."""
        ot = opx.op_type
        if ot in (
            OperatorType.REPARTITION,
            OperatorType.COMBINE,
            OperatorType.REPLICATE,
            OperatorType.REDUCTION,
        ):
            degree = opx.constraint_value("PM_PARALLEL_DEGREE")
            ff_dim = opx.constraint_value("PM_PARALLEL_DIM")
            if degree is None:
                raise ValueError(f"{self.name}: dst {ot} missing degree")
            params: Dict[str, object] = {"degree": degree}
            in_shape = g.shape_of(in_refs[0])
            if ot in (OperatorType.REPARTITION, OperatorType.COMBINE):
                axis = _ff_dim_to_axis(in_shape, ff_dim)
                if axis is None:
                    raise ValueError(f"{self.name}: bad dim {ff_dim}")
                params["axis"] = axis
            if ot == OperatorType.REPARTITION:
                # batch-dim partitions ride the data axis; everything else
                # (feature/channel dims) rides the model axis
                batch_axis = _nonreplica_axes(in_shape)[0]
                params["parallel_idx"] = (
                    0 if params["axis"] == batch_axis else self.model_axis
                )
            elif ot == OperatorType.REPLICATE:
                params["parallel_idx"] = self.model_axis
            return params

        # compute op: copy the matched same-type src op's params
        src_match = None
        for s_opx, guid in mapping.items():
            if s_opx.op_type == ot:
                if src_match is not None:
                    raise ValueError(
                        f"{self.name}: ambiguous param source for {ot}"
                    )
                src_match = old_graph.nodes[guid]
        params = dict(src_match.params) if src_match is not None else {}
        if src_match is not None:
            # stable identity for weight carry-over across recompiles: the
            # replacement node answers for the builder node whose params
            # (and so whose weights) it inherited, however many rewrites
            # deep (recompile_on_condition restores weights by this key)
            params["weight_key"] = src_match.params.get(
                "weight_key", src_match.name
            )
        acti = opx.constraint_value("PM_ACTI")
        if acti is not None:
            params["activation"] = _TASO_ACTI[acti]
        ff_axis = opx.constraint_value("PM_AXIS")
        if ff_axis is not None and in_refs:
            axis = _ff_dim_to_axis(g.shape_of(in_refs[0]), ff_axis)
            if axis is None:
                raise ValueError(f"{self.name}: bad axis {ff_axis}")
            params["axis"] = axis
        return params

    # -- one-shot driver ------------------------------------------------------

    def run(
        self, graph: PCGGraph, limit: int = 16
    ) -> Iterator[PCGGraph]:
        """Yield every valid single application of this rule to `graph`."""
        for mapping, binding in self.find_matches(graph, limit=limit):
            try:
                g, _ = self.apply(graph, mapping, binding)
            except (ValueError, KeyError):
                continue
            yield g

    def __repr__(self):
        return (
            f"GraphXfer('{self.name}', {len(self.src_ops)}→"
            f"{len(self.dst_ops)} ops)"
        )


# ---------------------------------------------------------------------------
# PM-parameter extraction from PCG nodes (match-time constraint evaluation)
# ---------------------------------------------------------------------------

# TASO generator activation encoding (see module docstring)
_TASO_ACTI = {0: ActiMode.NONE, 2: ActiMode.RELU}
_TASO_ACTI_REV = {v: k for k, v in _TASO_ACTI.items()}


def _nonreplica_axes(shape) -> List[int]:
    return [i for i, d in enumerate(shape.dims) if not d.is_replica_dim]


def _ff_dim_to_axis(shape, ff_dim: Optional[int]) -> Optional[int]:
    """ff-dim (innermost-first, replica past outermost) → numpy dims index."""
    if ff_dim is None:
        return None
    nr = _nonreplica_axes(shape)
    n = len(nr)
    if 0 <= ff_dim < n:
        return nr[n - 1 - ff_dim]
    return None  # ff_dim == n denotes the replica dim: no numpy axis


def _axis_to_ff_dim(shape, axis: int) -> Optional[int]:
    nr = _nonreplica_axes(shape)
    n = len(nr)
    if axis in nr:
        return n - 1 - nr.index(axis)
    return None


def _node_pm(graph: PCGGraph, node: PCGNode, key: str) -> Optional[int]:
    """Evaluate a PM_* key on a PCG node, in the rule file's conventions
    (the analog of Op::get_int_parameter on the reference side)."""
    ot = node.op_type
    in_shape = graph.shape_of(node.inputs[0]) if node.inputs else None

    if key == "PM_PARALLEL_DEGREE":
        if ot in (
            OperatorType.REPARTITION,
            OperatorType.COMBINE,
            OperatorType.REPLICATE,
            OperatorType.REDUCTION,
        ):
            return node.params.get("degree")
        return None
    if key == "PM_PARALLEL_DIM":
        if in_shape is None:
            return None
        if ot in (OperatorType.REPARTITION, OperatorType.COMBINE):
            return _axis_to_ff_dim(in_shape, node.params.get("axis"))
        if ot in (OperatorType.REPLICATE, OperatorType.REDUCTION):
            # replica dim position in ff convention = #non-replica dims
            return len(_nonreplica_axes(in_shape))
        return None
    if key == "PM_ACTI":
        acti = node.params.get("activation", ActiMode.NONE)
        return _TASO_ACTI_REV.get(acti)
    if key == "PM_AXIS":
        if in_shape is None:
            return None
        return _axis_to_ff_dim(in_shape, node.params.get("axis"))
    if key == "PM_NUM_INPUTS":
        return len(node.inputs)
    if key == "PM_NUM_OUTPUTS":
        return node.num_outputs
    if key == "PM_NUMDIM":
        out_shape = node.output_shapes[0] if node.output_shapes else None
        if out_shape is None:
            return None
        return len(_nonreplica_axes(out_shape))
    return None


# ---------------------------------------------------------------------------
# JSON rule loading (reference: substitution_loader.cc + create_xfers)
# ---------------------------------------------------------------------------

_JSON_OP_TYPES = {
    "OP_PARTITION": OperatorType.REPARTITION,
    "OP_COMBINE": OperatorType.COMBINE,
    "OP_REPLICATE": OperatorType.REPLICATE,
    "OP_REDUCE": OperatorType.REDUCTION,
    "OP_LINEAR": OperatorType.LINEAR,
    "OP_CONCAT": OperatorType.CONCAT,
    "OP_RELU": OperatorType.RELU,
    "OP_EW_ADD": OperatorType.EW_ADD,
    "OP_EW_MUL": OperatorType.EW_MUL,
    "OP_SPLIT": OperatorType.SPLIT,
    "OP_CONV2D": OperatorType.CONV2D,
    "OP_SOFTMAX": OperatorType.SOFTMAX,
    "OP_RESHAPE": OperatorType.RESHAPE,
    "OP_TRANSPOSE": OperatorType.TRANSPOSE,
}


def _rule_to_xfer(
    rule: dict, parallel_degree: int, model_axis: int
) -> GraphXfer:
    """Convert one JSON Rule to a GraphXfer
    (reference: create_xfer, substitution.cc:1587-1614)."""
    externals: Dict[Tuple[int, int], TensorX] = {}
    ext_counter = itertools.count()

    def external(op_id: int, ts_id: int) -> TensorX:
        key = (op_id, ts_id)
        if key not in externals:
            externals[key] = TensorX(None, next(ext_counter))
        return externals[key]

    def build(ops_json: List[dict]) -> List[OpX]:
        built: List[OpX] = []
        for op in ops_json:
            ot = _JSON_OP_TYPES.get(op["type"])
            if ot is None:
                raise ValueError(f"unsupported rule op type {op['type']}")
            inputs = []
            for t in op["input"]:
                if t["opId"] < 0:
                    inputs.append(external(t["opId"], t["tsId"]))
                else:
                    inputs.append(built[t["opId"]].out(t["tsId"]))
            constraints = []
            num_outputs = 1
            for p in op.get("para", []):
                key, value = p["key"], p["value"]
                if key == "PM_PARALLEL_DEGREEE":  # typo-proofing
                    key = "PM_PARALLEL_DEGREE"
                if key == "PM_PARALLEL_DEGREE":
                    # generated rules hardcode degree 2; generalize
                    # (reference: substitution.cc:1486-1488)
                    if value == 2:
                        value = parallel_degree
                if key == "PM_NUM_OUTPUTS":
                    num_outputs = value
                constraints.append(Constraint(key, value))
            built.append(OpX(ot, inputs, constraints, num_outputs))
        return built

    src_ops = build(rule["srcOp"])
    dst_ops = build(rule["dstOp"])
    mapped = [
        (
            src_ops[m["srcOpId"]].out(m["srcTsId"]),
            dst_ops[m["dstOpId"]].out(m["dstTsId"]),
        )
        for m in rule["mappedOutput"]
    ]
    return GraphXfer(rule["name"], src_ops, dst_ops, mapped, model_axis)


def load_substitution_rules(
    path: str, parallel_degree: int = 2, model_axis: int = 1
) -> List[GraphXfer]:
    """Load a TASO-generated rule collection JSON
    (reference: load_rule_collection_from_path + create_xfers; the file
    format of substitutions/graph_subst_3_v2.json)."""
    with open(path) as f:
        data = json.load(f)
    xfers = []
    for rule in data["rule"]:
        try:
            xfers.append(_rule_to_xfer(rule, parallel_degree, model_axis))
        except ValueError:
            continue  # rule uses an op outside our vocabulary
    return xfers


# ---------------------------------------------------------------------------
# Built-in hand-written xfers (reference: substitution.cc:1721-1862)
# ---------------------------------------------------------------------------


def create_linear_relu_merge(model_axis: int = 1) -> GraphXfer:
    """Linear(acti=none) → Relu  ⇒  Linear(acti=relu)
    (reference: create_linear_relu_merge, substitution.cc:3064-3090)."""
    x = TensorX(None, 0)
    lin = OpX(OperatorType.LINEAR, [x], [Constraint("PM_ACTI", 0)])
    relu = OpX(OperatorType.RELU, [lin.out()])
    fused = OpX(OperatorType.LINEAR, [x], [Constraint("PM_ACTI", 2)])
    return GraphXfer(
        "linear_relu_merge",
        [lin, relu],
        [fused],
        [(relu.out(), fused.out())],
        model_axis,
    )


# the bundled default rule collection (the analog of the reference's
# substitutions/graph_subst_3_v2.json, which ships with the repo and loads
# without any flag) — hand-authored for the TPU rebuild, see the file's
# _comment fields
DEFAULT_RULES_PATH = os.path.join(
    os.path.dirname(__file__), "substitutions", "default_rules.json"
)


def default_xfers(parallel_degree: int, model_axis: int = 1) -> List[GraphXfer]:
    """The built-in rewrite set: the hand-written builders plus the bundled
    default rule collection (reference: ship-with-repo rule files used as a
    core search phase, SURVEY §2.5)."""
    xfers = [create_linear_relu_merge(model_axis)]
    xfers += load_substitution_rules(
        DEFAULT_RULES_PATH, parallel_degree, model_axis
    )
    return xfers


# ---------------------------------------------------------------------------
# Cost-bounded substitution search (reference: base_optimize,
# substitution.cc:2112-2194 — priority-queue rewrite search)
# ---------------------------------------------------------------------------


def apply_substitution_pass(
    graph: PCGGraph,
    logits_ref: TensorRef,
    cfg,
    mesh_config,
) -> Tuple[PCGGraph, TensorRef]:
    """compile()-time substitution optimization
    (reference: GraphSearchHelper::graph_optimize's base_optimize loop over
    GraphXfers, substitution.cc:2112-2194; invoked when --substitution-json
    or --fusion is set — under XLA the fusion payoff is folded into the
    rewrite set since the compiler already fuses elementwise chains).

    Tracks the logits tensor across rewrites by pinning it with a sentinel
    IDENTITY consumer (rewired by mapped-output routing like any consumer),
    and returns (optimized graph, surviving logits ref).
    """
    from flexflow_tpu.core.machine import MachineSpec
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.simulator import estimate_graph_cost

    mesh_sizes = tuple(mesh_config.axis_sizes)
    model_degree = mesh_sizes[1] if len(mesh_sizes) > 1 else 2
    model_axis = 1 if len(mesh_sizes) > 1 else 0

    # --no-substitution drops the bundled default rule set even when the
    # pass itself still runs for an explicit --substitution-json/--fusion
    xfers = (
        default_xfers(model_degree, model_axis)
        if getattr(cfg, "enable_substitution", True)
        else []
    )
    if cfg.substitution_json:
        xfers += load_substitution_rules(
            cfg.substitution_json, model_degree, model_axis
        )

    g = graph.copy()
    sentinel = g.add_node(
        OperatorType.IDENTITY, "__logits_sentinel__", [logits_ref], {},
        [g.shape_of(logits_ref)],
    )

    spec = MachineSpec(
        num_nodes=max(1, cfg.num_nodes),
        chips_per_node=max(
            1, mesh_config.num_devices // max(1, cfg.num_nodes)
        ),
        chip=cfg.chip,
    )
    cm = CostModel(
        spec, measure=False, mixed_precision=cfg.allow_mixed_precision
    )

    def cost_fn(gr: PCGGraph) -> float:
        # degrees must actually be expressible on the mesh: without this
        # guard the simulator REWARDS stacking partition rules past the
        # device count (smaller pieces look faster), and the executor later
        # mis-shards or rejects the annotation (partition_spec span check)
        for node in gr.nodes.values():
            for s in list(node.output_shapes) + list(node.weight_shapes):
                if not s.is_valid_for_mesh(mesh_sizes):
                    return float("inf")
        try:
            return estimate_graph_cost(gr, cm, mesh_sizes).step_time
        except (ValueError, KeyError):
            return float("inf")

    budget = cfg.search_budget if cfg.search_budget > 0 else 50
    best, _ = base_optimize(
        g, xfers, cost_fn, budget=budget, alpha=cfg.search_alpha
    )

    snode = best.nodes[sentinel.guid]
    new_logits = snode.inputs[0]
    best.remove_node(sentinel.guid)
    return best, new_logits


def base_optimize(
    graph: PCGGraph,
    xfers: Sequence[GraphXfer],
    cost_fn: Callable[[PCGGraph], float],
    budget: int = 100,
    alpha: float = 1.05,
    max_matches_per_xfer: int = 8,
) -> Tuple[PCGGraph, float]:
    """Best-first search over rule applications.

    Pops the cheapest graph, applies every rule at every match site, keeps
    candidates whose cost is within `alpha ×` the best seen (the reference's
    pruning factor), stops after `budget` cost evaluations.
    """
    best = graph
    best_cost = cost_fn(graph)
    seen = {graph.hash()}
    counter = itertools.count()
    pq: List[Tuple[float, int, PCGGraph]] = [(best_cost, next(counter), graph)]
    evals = 0

    while pq and evals < budget:
        cost, _, g = heapq.heappop(pq)
        if cost > alpha * best_cost:
            continue
        for xfer in xfers:
            for new_g in xfer.run(g, limit=max_matches_per_xfer):
                h = new_g.hash()
                if h in seen:
                    continue
                seen.add(h)
                c = cost_fn(new_g)
                evals += 1
                if c < best_cost:
                    best, best_cost = new_g, c
                if c <= alpha * best_cost:
                    heapq.heappush(pq, (c, next(counter), new_g))
                if evals >= budget:
                    return best, best_cost
    return best, best_cost
