"""Parallelization rewrites: the substitution vocabulary of the search.

TPU re-design of the reference's hand-written GraphXfer generators
(reference: src/runtime/substitution.cc:1721-1862). Each rewrite wraps a
matched subgraph in parallel ops so the existing shape-inference protocol
(replica dim -> channel/head sharding; partitioned contraction dim ->
partial-sum replica dim) expresses the strategy:

  * `LinearChainSite` — Megatron column→row pair
    (reference: create_replicate_linear_combine + the reduction variant,
    substitution.cc:1750-1765,1804-1827): Replicate(x) → Linear(out-sharded)
    → …elementwise… → Linear(partial sums) → Reduction.
  * `AttentionSite` — head parallelism
    (reference: create_replicate_attention_reduce, substitution.cc:1758-1764):
    Replicate(q,k,v) → MHA (heads sharded, output partial) → Reduction.
  * `SingleLinearSite` — lone Linear: Replicate → Linear → Combine on the
    feature dim (column-parallel only; reference:
    create_partition_linear_combine).

A "site" is a detected location; `apply(graph, tp, axis)` mutates the graph.
Sites are the unit the search toggles on/off.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import OperatorType

# elementwise ops a sharded feature dim passes through unchanged
_PASSTHROUGH = {
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.ELU,
    OperatorType.GELU,
    OperatorType.IDENTITY,
    OperatorType.EXP,
    OperatorType.SIN,
    OperatorType.COS,
    OperatorType.POW,
    OperatorType.RSQRT,
    OperatorType.SCALAR_MULTIPLY,
    OperatorType.SCALAR_ADD,
    OperatorType.SCALAR_SUB,
    OperatorType.SCALAR_TRUE_DIV,
    OperatorType.DROPOUT,
}


def _insert_before(
    graph: PCGGraph,
    consumer_guid: int,
    input_ref: TensorRef,
    op_type: OperatorType,
    name: str,
    params: dict,
) -> TensorRef:
    """Insert `op_type(input_ref)` and rewire ONLY consumer_guid to it.

    Output shapes are placeholders (the producer's current shape): upstream
    rewrites may not have re-propagated yet, so real shapes are only
    computable by the caller's final propagate_shapes pass."""
    in_shape = graph.shape_of(input_ref)
    node = graph.add_node(op_type, name, [input_ref], params, [in_shape])
    new_ref = TensorRef(node.guid, 0)
    graph.replace_input(consumer_guid, input_ref, new_ref)
    return new_ref


def _insert_after(
    graph: PCGGraph,
    producer_guid: int,
    op_type: OperatorType,
    name: str,
    params: dict,
) -> TensorRef:
    """Insert `op_type(producer:0)` and rewire ALL other consumers to it.
    Placeholder output shapes, like _insert_before."""
    src = TensorRef(producer_guid, 0)
    consumers = graph.consumers(producer_guid)
    in_shape = graph.shape_of(src)
    node = graph.add_node(op_type, name, [src], params, [in_shape])
    new_ref = TensorRef(node.guid, 0)
    for c in consumers:
        graph.replace_input(c, src, new_ref)
    return new_ref


@dataclasses.dataclass(frozen=True)
class Site:
    kind: str
    guids: Tuple[int, ...]  # nodes involved, in chain order

    def divisible_by(self, graph: PCGGraph, tp: int) -> bool:
        raise NotImplementedError

    def apply(self, graph: PCGGraph, tp: int, axis: int):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class LinearChainSite(Site):
    """linear → elementwise* → linear, all intermediates single-consumer."""

    def divisible_by(self, graph, tp):
        a = graph.nodes[self.guids[0]]
        return a.params["out_features"] % tp == 0

    def apply(self, graph, tp, axis):
        a_guid, b_guid = self.guids[0], self.guids[-1]
        a = graph.nodes[a_guid]
        _insert_before(
            graph,
            a_guid,
            a.inputs[0],
            OperatorType.REPLICATE,
            f"{a.name}.replicate",
            {"degree": tp, "parallel_idx": axis},
        )
        b = graph.nodes[b_guid]
        _insert_after(
            graph,
            b_guid,
            OperatorType.REDUCTION,
            f"{b.name}.reduction",
            {"degree": tp},
        )


@dataclasses.dataclass(frozen=True)
class AttentionSite(Site):
    """One MultiHeadAttention node; q/k/v may be the same tensor."""

    def divisible_by(self, graph, tp):
        node = graph.nodes[self.guids[0]]
        return node.params["num_heads"] % tp == 0

    def apply(self, graph, tp, axis):
        guid = self.guids[0]
        node = graph.nodes[guid]
        # one Replicate per unique input; replace_input rewires every
        # occurrence of a duplicated ref (q=k=v) in one call
        for i, ref in enumerate(dict.fromkeys(node.inputs)):
            _insert_before(
                graph,
                guid,
                ref,
                OperatorType.REPLICATE,
                f"{node.name}.replicate{i}",
                {"degree": tp, "parallel_idx": axis},
            )
        _insert_after(
            graph,
            guid,
            OperatorType.REDUCTION,
            f"{node.name}.reduction",
            {"degree": tp},
        )


@dataclasses.dataclass(frozen=True)
class _ColumnParallelSite(Site):
    """Shared column-parallel bracket: Replicate the (single) input, let
    the replica-dim protocol shard the op's width param over the model
    axis, Combine gathers the last (feature/channel) output dim after.
    Subclasses name the width param; one implementation means a protocol
    fix lands everywhere at once."""

    _WIDTH_PARAM = ""  # subclass sets

    def divisible_by(self, graph, tp):
        return graph.nodes[self.guids[0]].params[self._WIDTH_PARAM] % tp == 0

    def apply(self, graph, tp, axis):
        guid = self.guids[0]
        node = graph.nodes[guid]
        _insert_before(
            graph,
            guid,
            node.inputs[0],
            OperatorType.REPLICATE,
            f"{node.name}.replicate",
            {"degree": tp, "parallel_idx": axis},
        )
        # output feature/channel dim comes out sharded; Combine gathers it
        out_ndim = len(node.output_shapes[0].dims)
        _insert_after(
            graph,
            guid,
            OperatorType.COMBINE,
            f"{node.name}.combine",
            {"axis": out_ndim - 1, "degree": tp},
        )


@dataclasses.dataclass(frozen=True)
class SingleLinearSite(_ColumnParallelSite):
    """A lone Linear: column-parallel, gather features after."""

    _WIDTH_PARAM = "out_features"


@dataclasses.dataclass(frozen=True)
class ConvChannelSite(_ColumnParallelSite):
    """One Conv2D: shard the OUTPUT-channel dim over the model axis
    (reference: conv mapping xfers, create_mapping_xfers<Conv2D>,
    substitution.cc:1789 — the conv analog of column-parallel Linear)."""

    _WIDTH_PARAM = "out_channels"

    def divisible_by(self, graph, tp):
        node = graph.nodes[self.guids[0]]
        groups = node.params.get("groups", 1)
        # grouped convs: sharding across group boundaries is not
        # partitionable (XLA SPMD aborts on it); tp must divide the groups
        return (
            node.params["out_channels"] % tp == 0
            and (groups == 1 or groups % tp == 0)
        )


@dataclasses.dataclass(frozen=True)
class EmbeddingSite(_ColumnParallelSite):
    """Model-parallel embedding: shard the table's embedding (out_dim)
    column dim over the model axis — the reference's key DLRM pattern
    ("embedding weight sharded or replicated", embedding.cc; DLRM
    strategies shard tables while the MLPs stay data-parallel)."""

    _WIDTH_PARAM = "out_dim"


@dataclasses.dataclass(frozen=True)
class ExpertParallelSite(Site):
    """Batched ExpertFFN + its Aggregate consumer: shard the expert dim
    over the model axis (GShard-style EP; the reference instead lets the
    search place per-expert Linear ops on different GPUs)."""

    def divisible_by(self, graph, tp):
        ffn = graph.nodes[self.guids[0]]
        n = graph.shape_of(ffn.inputs[0]).dims[0].size
        return n % tp == 0

    def apply(self, graph, tp, axis):
        ffn_guid, agg_guid = self.guids
        ffn = graph.nodes[ffn_guid]
        # scatter the stacked [n, cap, d] tensor's expert dim over the axis
        _insert_before(
            graph,
            ffn_guid,
            ffn.inputs[0],
            OperatorType.REPARTITION,
            f"{ffn.name}.repartition",
            {"axis": 0, "degree": tp, "parallel_idx": axis},
        )
        # aggregate contracts the (sharded) expert dim -> partial sums
        _insert_after(
            graph,
            agg_guid,
            OperatorType.REDUCTION,
            f"{graph.nodes[agg_guid].name}.reduction",
            {"degree": tp},
        )


def find_tp_sites(graph: PCGGraph) -> List[Site]:
    """Detect tensor-parallel rewrite sites (the search's substitution
    candidates). Linear pairs are preferred over two singles; attention
    nodes are always sites."""
    sites: List[Site] = []
    claimed = set()

    for guid in graph.topo_order():
        node = graph.nodes[guid]
        if node.op_type == OperatorType.MULTIHEAD_ATTENTION:
            sites.append(AttentionSite("attention", (guid,)))
            claimed.add(guid)
        elif node.op_type == OperatorType.EMBEDDING:
            sites.append(EmbeddingSite("embedding", (guid,)))
            claimed.add(guid)
        elif node.op_type == OperatorType.CONV2D:
            sites.append(ConvChannelSite("conv_channel", (guid,)))
            claimed.add(guid)
        elif node.op_type == OperatorType.EXPERT_FFN:
            aggs = [
                c
                for c in graph.consumers(guid)
                if graph.nodes[c].op_type
                in (OperatorType.AGGREGATE, OperatorType.AGGREGATE_SPEC)
            ]
            if len(aggs) == 1:
                sites.append(
                    ExpertParallelSite("expert_parallel", (guid, aggs[0]))
                )
                claimed.update({guid, aggs[0]})

    # linear→elementwise*→linear chains
    for guid in graph.topo_order():
        node = graph.nodes[guid]
        if node.op_type != OperatorType.LINEAR or guid in claimed:
            continue
        chain = [guid]
        cur = guid
        ok = False
        while True:
            cons = graph.consumers(cur)
            if len(cons) != 1:
                break
            nxt = next(iter(cons))
            nxt_node = graph.nodes[nxt]
            if nxt_node.op_type == OperatorType.LINEAR and nxt not in claimed:
                chain.append(nxt)
                ok = True
                break
            if nxt_node.op_type in _PASSTHROUGH:
                chain.append(nxt)
                cur = nxt
                continue
            break
        if ok:
            sites.append(LinearChainSite("linear_chain", tuple(chain)))
            claimed.update(chain)

    # leftover lone linears (not the tiny final classifier — searching it
    # is allowed, the cost model will reject unprofitable ones anyway)
    for guid in graph.topo_order():
        node = graph.nodes[guid]
        if node.op_type == OperatorType.LINEAR and guid not in claimed:
            sites.append(SingleLinearSite("single_linear", (guid,)))
            claimed.add(guid)
    return sites
