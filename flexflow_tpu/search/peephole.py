"""Graph peepholes: the TPU analogs of the reference's remaining
hand-written GraphXfer generators (reference: src/runtime/substitution.cc
generate_all_pcg_xfers, :1721-1862).

Two passes:

  * `fuse_linear_activation` — create_linear_relu_merge (:1830s): fold a
    single-consumer RELU/SIGMOID/TANH/GELU node into the producing
    Linear's `activation` param. Beyond the fused-kernel saving (which
    XLA largely gets anyway), the searchable effect is PLACEMENT: under a
    column-parallel site the fused activation runs on the sharded output
    BEFORE the Combine gather, where the standalone node ran replicated
    after it.
  * `sink_combines` — the partition-move family
    (create_partition_{add,relu,softmax,concat}_combine,
    create_combine_concat / create_combine_inception, :1721-1827): move a
    Combine gather DOWN through ops that commute with the combined axis —
    elementwise unaries, matching-axis Adds, BatchNorm on its channel
    axis, Softmax off its softmax axis, Concat when every input arrives
    through a matching Combine. Each sink makes the downstream op compute
    on 1/degree of the data; N sibling Combines below a Concat collapse
    into one. Run after site application (parallel/strategy.py,
    search/auto.py) so the costed candidate and the lowered graph agree.

Both passes preserve guids of surviving nodes (pipeline templates and
site tuples reference them).
"""

from __future__ import annotations

from typing import Optional

from flexflow_tpu.core.pcg import PCGGraph, TensorRef
from flexflow_tpu.core.types import ActiMode, OperatorType

# standalone activation node -> Linear activation param
_FUSABLE_ACTIVATIONS = {
    OperatorType.RELU: ActiMode.RELU,
    OperatorType.SIGMOID: ActiMode.SIGMOID,
    OperatorType.TANH: ActiMode.TANH,
    OperatorType.GELU: ActiMode.GELU,
}

# unary elementwise ops any Combine axis passes through
_SINK_UNARY = {
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.ELU,
    OperatorType.GELU,
    OperatorType.IDENTITY,
    OperatorType.EXP,
    OperatorType.SIN,
    OperatorType.COS,
    OperatorType.POW,
    OperatorType.RSQRT,
    OperatorType.SCALAR_MULTIPLY,
    OperatorType.SCALAR_ADD,
    OperatorType.SCALAR_SUB,
    OperatorType.SCALAR_TRUE_DIV,
    OperatorType.DROPOUT,
}


def fuse_linear_activation(graph: PCGGraph) -> int:
    """Fold standalone activation nodes into their producing Linear.
    Mutates `graph`; returns the number of fusions."""
    fused = 0
    for guid in list(graph.topo_order()):
        node = graph.nodes.get(guid)
        if node is None or node.op_type not in _FUSABLE_ACTIVATIONS:
            continue
        if len(node.inputs) != 1:
            continue
        src = node.inputs[0]
        prod = graph.nodes.get(src.guid)
        if prod is None or prod.op_type != OperatorType.LINEAR:
            continue
        if prod.params.get("activation", ActiMode.NONE) != ActiMode.NONE:
            continue
        # the linear must feed ONLY this activation (otherwise other
        # consumers would see activated values)
        if graph.consumers(src.guid) != {guid}:
            continue
        prod.params["activation"] = _FUSABLE_ACTIVATIONS[node.op_type]
        for c in list(graph.consumers(guid)):
            graph.replace_input(c, TensorRef(guid, 0), src)
        graph.remove_node(guid)
        fused += 1
    return fused


def _single_combine_in(graph: PCGGraph, ref: TensorRef):
    """The Combine node feeding `ref`, if `ref` is a Combine output with
    no other consumers (safe to re-home)."""
    node = graph.nodes.get(ref.guid)
    if node is None or node.op_type != OperatorType.COMBINE:
        return None
    return node


def _abs_axis(shape, logical_axis: int) -> int:
    """Absolute index (into shape.dims, replica dims included) of the
    logical axis — Combine params address dims absolutely."""
    cnt = -1
    for i, d in enumerate(shape.dims):
        if not d.is_replica_dim:
            cnt += 1
            if cnt == logical_axis:
                return i
    return len(shape.dims) - 1


def _sink_one(graph: PCGGraph, guid: int) -> bool:
    """Try to sink the Combine(s) feeding node `guid` below it."""
    node = graph.nodes.get(guid)
    if node is None or node.op_type == OperatorType.COMBINE:
        return False
    combines = []
    for ref in node.inputs:
        c = _single_combine_in(graph, ref)
        combines.append((ref, c))
    live = [(r, c) for r, c in combines if c is not None]
    if not live:
        return False

    def consumers_only_me(c_guid: int) -> bool:
        return graph.consumers(c_guid) == {guid}

    op = node.op_type
    if op in _SINK_UNARY and len(node.inputs) == 1:
        ref, comb = combines[0]
        if not consumers_only_me(comb.guid):
            return False
        movers = [comb]
    elif op == OperatorType.EW_ADD:
        # both inputs must arrive through IDENTICAL combines
        if len(combines) != 2 or any(c is None for _, c in combines):
            return False
        (r1, c1), (r2, c2) = combines
        if (
            c1.params.get("axis") != c2.params.get("axis")
            or c1.params.get("degree") != c2.params.get("degree")
            or not consumers_only_me(c1.guid)
            or not consumers_only_me(c2.guid)
        ):
            return False
        movers = [c1, c2]
    elif op == OperatorType.SOFTMAX:
        ref, comb = combines[0]
        sm_dim = node.params.get("dim", -1)
        in_shape = graph.shape_of(comb.inputs[0])
        nd = len([d for d in in_shape.dims if not d.is_replica_dim])
        if sm_dim < 0:
            sm_dim += nd
        if comb.params.get("axis") == _abs_axis(
            in_shape, sm_dim
        ) or not consumers_only_me(comb.guid):
            return False
        movers = [comb]
    elif op == OperatorType.BATCHNORM:
        # BN statistics are PER-CHANNEL: a channel-axis combine commutes
        # (each shard owns whole channels); any other axis would split
        # the reduction and does not
        ref, comb = combines[0]
        in_shape = graph.shape_of(comb.inputs[0])
        if comb.params.get("axis") != len(
            in_shape.dims
        ) - 1 or not consumers_only_me(comb.guid):
            return False
        movers = [comb]
    elif op == OperatorType.CONCAT:
        # every input must arrive through a matching-degree combine on the
        # SAME logical axis (create_combine_concat: N combines + concat ->
        # concat + 1 combine)
        if len(live) != len(combines) or not combines:
            return False
        axis0 = combines[0][1].params.get("axis")
        deg0 = combines[0][1].params.get("degree")
        for _, c in combines:
            if (
                c.params.get("axis") != axis0
                or c.params.get("degree") != deg0
                or not consumers_only_me(c.guid)
            ):
                return False
        movers = [c for _, c in combines]
    else:
        return False

    # rewire: node consumes the combines' inputs directly; one new Combine
    # (same params as the first mover) takes the node's output; the old
    # combine nodes disappear. Dedupe movers: add(y, y) feeds the SAME
    # combine through both inputs and it must be removed exactly once.
    movers = list({c.guid: c for c in movers}.values())
    params = dict(movers[0].params)
    for ref, comb in combines:
        if comb is not None:
            graph.replace_input(guid, ref, comb.inputs[0])
    from flexflow_tpu.search.rewrites import _insert_after

    _insert_after(
        graph,
        guid,
        OperatorType.COMBINE,
        f"{node.name}.combine_sunk",
        params,
    )
    for comb in movers:
        graph.remove_node(comb.guid)
    return True


def sink_combines(graph: PCGGraph, max_passes: int = 32) -> int:
    """Repeatedly sink Combine nodes until fixpoint. Returns total sinks."""
    total = 0
    for _ in range(max_passes):
        moved = False
        for guid in list(graph.topo_order()):
            if guid in graph.nodes and _sink_one(graph, guid):
                moved = True
                total += 1
        if not moved:
            break
    return total
