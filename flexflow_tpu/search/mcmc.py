"""MCMC strategy search (the legacy engine).

Rebuild of the reference's simulated-annealing search over per-op
ParallelConfigs (reference: FFModel::mcmc_optimize, model.cc:3271-3342,
driven by Simulator::strategy_search_task): start from the data-parallel
config, repeatedly pick a random op and a random valid machine view
(reference: rewrite(), model.cc:3246), score the whole config with the
simulator, accept improvements always and regressions with probability
exp(-alpha * delta), periodically resetting to the best-so-far.

The view vocabulary, per-(op, view) costs and transfer estimates are shared
with the Unity DP engine (search.unity.UnitySearch); the full-config score
is the analytic sum the reference's LogicalTaskgraphBasedSimulator computes
(simulator.h:776-818).
"""

from __future__ import annotations

import math
import random
from typing import Dict, Optional

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.search.unity import UnityResult, UnitySearch, ViewOption


def simulate_config(
    search: UnitySearch, views: Dict[int, ViewOption]
) -> float:
    """Step-time of one full per-op view assignment: op costs + transfer
    cost on every producer→consumer edge whose views differ."""
    g = search.graph
    total = 0.0
    for guid, view in views.items():
        total += search.op_cost(guid, view)
        for ref in g.nodes[guid].inputs:
            if ref.guid in views:
                total += search.xfer_cost(ref, views[ref.guid], view)
    return total


def config_delta(
    search: UnitySearch,
    views: Dict[int, ViewOption],
    guid: int,
    new_view: ViewOption,
) -> float:
    """Cost change from flipping one node's view: only its op cost and the
    transfers on its incident edges move (a full re-simulation per proposal
    would make the budget loop O(V+E) per step for no gain)."""
    g = search.graph
    old = views[guid]
    d = search.op_cost(guid, new_view) - search.op_cost(guid, old)
    for ref in g.nodes[guid].inputs:
        if ref.guid in views:
            d += search.xfer_cost(ref, views[ref.guid], new_view)
            d -= search.xfer_cost(ref, views[ref.guid], old)
    for c in g.consumers(guid):
        if c in views:
            for ref in g.nodes[c].inputs:
                if ref.guid == guid:
                    d += search.xfer_cost(ref, new_view, views[c])
                    d -= search.xfer_cost(ref, old, views[c])
    return d


def mcmc_optimize(
    graph: PCGGraph,
    spec: MachineSpec,
    budget: int = 100,
    alpha: float = 1.05,
    seed: int = 0,
    verbose: bool = False,
    machine_model=None,
    mixed_precision: bool = False,
    measure: bool = False,
    calibration_file: str = "",
    sparse_embedding: bool = True,
) -> UnityResult:
    """reference: mcmc_optimize (model.cc:3271) — budget proposals, periodic
    reset to best every budget/10 non-improving steps."""
    search = UnitySearch(
        graph, spec, machine_model=machine_model,
        mixed_precision=mixed_precision,
        measure=measure,
        calibration_file=calibration_file,
        sparse_embedding=sparse_embedding,
    )
    resource = search.resource
    rng = random.Random(seed)
    guids = [
        g
        for g in graph.topo_order()
        if graph.nodes[g].op_type.name != "INPUT"
    ]

    # start from data-parallel-over-all-chips where valid (reference seeds
    # MCMC with the data-parallel strategy too)
    def default_view(g):
        cands = search.valid_views(g, resource)
        full = [
            v
            for v in cands
            if v.ch == 1 and v.num_devices == resource.num_chips
        ]
        return full[0] if full else cands[0]

    cur = {g: default_view(g) for g in guids}
    cur_cost = simulate_config(search, cur)
    best, best_cost = dict(cur), cur_cost
    since_best = 0
    reset_every = max(budget // 10, 10)

    for it in range(budget):
        g = rng.choice(guids)
        cands = search.valid_views(g, resource)
        nxt_view = rng.choice(cands)
        if nxt_view.key() == cur[g].key():
            continue
        delta = config_delta(search, cur, g, nxt_view)
        scale = max(cur_cost, 1e-9)
        if delta < 0 or rng.random() < math.exp(-alpha * delta / scale):
            cur = dict(cur)
            cur[g] = nxt_view
            cur_cost += delta
        if cur_cost < best_cost:
            best, best_cost = dict(cur), cur_cost
            since_best = 0
        else:
            since_best += 1
            if since_best >= reset_every:  # reference: periodic reset to best
                cur, cur_cost = dict(best), best_cost
                since_best = 0
        if verbose and it % max(budget // 10, 1) == 0:
            print(
                f"[mcmc] iter {it}: current {cur_cost * 1e3:.3f} ms, "
                f"best {best_cost * 1e3:.3f} ms"
            )
    return UnityResult(best_cost, best)
