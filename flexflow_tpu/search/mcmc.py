"""MCMC strategy search (the legacy engine).

Rebuild of the reference's simulated-annealing search over per-op
ParallelConfigs (reference: FFModel::mcmc_optimize, model.cc:3271-3342,
driven by Simulator::strategy_search_task): start from the data-parallel
config, repeatedly pick a random op and a random valid machine view
(reference: rewrite(), model.cc:3246), score the whole config with the
simulator, accept improvements always and regressions with probability
exp(-alpha * delta), periodically resetting to the best-so-far.

The view vocabulary, per-(op, view) costs and transfer estimates are shared
with the Unity DP engine (search.unity.UnitySearch); the full-config score
is the analytic sum the reference's LogicalTaskgraphBasedSimulator computes
(simulator.h:776-818).
"""

from __future__ import annotations

import math
import random
from typing import Dict, Optional

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.search.unity import UnityResult, UnitySearch, ViewOption


def simulate_config(
    search: UnitySearch, views: Dict[int, ViewOption]
) -> float:
    """Step-time of one full per-op view assignment: op costs + transfer
    cost on every producer→consumer edge whose views differ."""
    g = search.graph
    total = 0.0
    for guid, view in views.items():
        total += search.op_cost(guid, view)
        for ref in g.nodes[guid].inputs:
            if ref.guid in views:
                total += search.xfer_cost(ref, views[ref.guid], view)
    return total


def config_delta(
    search: UnitySearch,
    views: Dict[int, ViewOption],
    guid: int,
    new_view: ViewOption,
) -> float:
    """Cost change from flipping one node's view: only its op cost and the
    transfers on its incident edges move (a full re-simulation per proposal
    would make the budget loop O(V+E) per step for no gain)."""
    g = search.graph
    old = views[guid]
    d = search.op_cost(guid, new_view) - search.op_cost(guid, old)
    for ref in g.nodes[guid].inputs:
        if ref.guid in views:
            d += search.xfer_cost(ref, views[ref.guid], new_view)
            d -= search.xfer_cost(ref, views[ref.guid], old)
    for c in g.consumers(guid):
        if c in views:
            for ref in g.nodes[c].inputs:
                if ref.guid == guid:
                    d += search.xfer_cost(ref, new_view, views[c])
                    d -= search.xfer_cost(ref, old, views[c])
    return d


# reference: FFModel constants (model.h:325-327). PROPAGATION_SIZE_WEIGHT
# is 1.0 there, i.e. pure tensor-volume weighting of the walk edges.
PROPAGATION_CHANCE = 0.25
CONTINUE_PROPAGATION_CHANCE = 0.75


def propagate_views(
    search: UnitySearch,
    views: Dict[int, ViewOption],
    start: int,
    rng: random.Random,
) -> Dict[int, ViewOption]:
    """Frontier propagation (reference: FFModel::propagate,
    model.cc:3166-3246, FF_USE_PROPAGATE): walk a random path from `start`
    over producer/consumer edges, weighted by edge-tensor volume, copying
    the start node's CURRENT view onto each visited neighbor that can
    adopt it (has an equal-key view among its valid options); continue
    with probability CONTINUE_PROPAGATION_CHANCE. Returns the proposed
    {guid: view} reassignments (empty when no neighbor is adoptable) —
    the caller scores/accepts the whole move atomically."""
    g = search.graph
    assignments: Dict[int, ViewOption] = {}
    seen = {start}
    cur = start
    view = views[start]

    def volume(ref) -> float:
        shape = g.shape_of(ref)
        v = 1.0
        for d in shape.dims:
            if not d.is_replica_dim:
                v *= d.size
        return v

    while True:
        candidates = []
        node = g.nodes[cur]
        for ref in node.inputs:
            n = ref.guid
            if n in views and n not in seen:
                candidates.append((n, volume(ref)))
        for c in g.consumers(cur):
            if c in views and c not in seen:
                for ref in g.nodes[c].inputs:
                    if ref.guid == cur:
                        candidates.append((c, volume(ref)))
                        break
        adoptable = []
        for n, vol in candidates:
            match = next(
                (
                    v
                    for v in search.valid_views(n, search.resource)
                    if v.key() == view.key()
                ),
                None,
            )
            if match is not None:
                adoptable.append((n, vol, match))
        if not adoptable:
            break
        total = sum(vol for _, vol, _ in adoptable)
        r = rng.random() * (total if total > 0 else len(adoptable))
        acc = 0.0
        chosen = adoptable[-1]
        for item in adoptable:
            acc += item[1] if total > 0 else 1.0
            if r <= acc:
                chosen = item
                break
        n, _, match = chosen
        assignments[n] = match
        seen.add(n)
        cur = n
        if rng.random() >= CONTINUE_PROPAGATION_CHANCE:
            break
    return assignments


def mcmc_optimize(
    graph: PCGGraph,
    spec: MachineSpec,
    budget: int = 100,
    alpha: float = 1.05,
    seed: int = 0,
    verbose: bool = False,
    machine_model=None,
    mixed_precision: bool = False,
    measure: bool = False,
    calibration_file: str = "",
    sparse_embedding: bool = True,
    use_propagation: bool = True,
    trace=None,
) -> UnityResult:
    """reference: mcmc_optimize (model.cc:3271) — budget proposals, periodic
    reset to best every budget/10 non-improving steps.

    All randomness flows from the explicit `seed` through one private
    `random.Random` — no global RNG state is read, so a run is
    reproducible from its arguments alone, and the `trace`
    (telemetry.SearchTrace) records seed + temperature schedule +
    accept/reject tallies so it is reproducible from the ARTIFACT
    alone: every proposal lands in the trace with its cost delta and
    verdict."""
    reset_every = max(budget // 10, 10)
    if trace is not None:
        # the header carries everything a rerun needs: the acceptance
        # rule is exp(-alpha * delta / current_cost) at constant alpha
        # (the reference's annealing "temperature" is this fixed alpha
        # over a cost-relative delta), reset-to-best every reset_every
        # non-improving proposals
        trace.header(
            engine="mcmc",
            seed=seed,
            budget=budget,
            alpha=alpha,
            temperature={
                "kind": "constant-alpha",
                "alpha": alpha,
                "acceptance": "exp(-alpha*delta/cur_cost)",
                "reset_every": reset_every,
            },
            propagation=bool(use_propagation),
            measure=bool(measure),
        )
    search = UnitySearch(
        graph, spec, machine_model=machine_model,
        mixed_precision=mixed_precision,
        measure=measure,
        calibration_file=calibration_file,
        sparse_embedding=sparse_embedding,
        trace=trace,
    )
    resource = search.resource
    rng = random.Random(seed)
    guids = [
        g
        for g in graph.topo_order()
        if graph.nodes[g].op_type.name != "INPUT"
    ]

    # start from data-parallel-over-all-chips where valid (reference seeds
    # MCMC with the data-parallel strategy too)
    def default_view(g):
        cands = search.valid_views(g, resource)
        full = [
            v
            for v in cands
            if v.ch == 1 and v.num_devices == resource.num_chips
        ]
        return full[0] if full else cands[0]

    from contextlib import nullcontext

    def _phase(name):
        return trace.phase(name) if trace is not None else nullcontext()

    with _phase("mcmc:init"):
        cur = {g: default_view(g) for g in guids}
        cur_cost = simulate_config(search, cur)
    best, best_cost = dict(cur), cur_cost

    since_best = 0
    # the anneal loop is one phase span; entered/exited manually so the
    # (long) loop body keeps its indentation
    anneal_cm = _phase("mcmc:anneal")
    anneal_cm.__enter__()
    for it in range(budget):
        # reference: rewrite() (model.cc:3247-3269) — with probability
        # PROPAGATION_CHANCE propose a frontier propagation instead of a
        # single-op flip
        if use_propagation and rng.random() < PROPAGATION_CHANCE:
            kind = "propagate"
            g = rng.choice(guids)
            assigns = propagate_views(search, cur, g, rng)
            if not assigns:
                continue
            trial = dict(cur)
            delta = 0.0
            for n, v in assigns.items():
                delta += config_delta(search, trial, n, v)
                trial[n] = v
            new_dp = new_ch = None
            ops_changed = len(assigns)
        else:
            kind = "flip"
            g = rng.choice(guids)
            cands = search.valid_views(g, resource)
            nxt_view = rng.choice(cands)
            if nxt_view.key() == cur[g].key():
                continue
            trial = dict(cur)
            trial[g] = nxt_view
            delta = config_delta(search, cur, g, nxt_view)
            new_dp, new_ch = nxt_view.dp, nxt_view.ch
            ops_changed = 1
        scale = max(cur_cost, 1e-9)
        accepted = bool(
            delta < 0 or rng.random() < math.exp(-alpha * delta / scale)
        )
        if accepted:
            cur = trial
            cur_cost += delta
        if cur_cost < best_cost:
            best, best_cost = dict(cur), cur_cost
            since_best = 0
        else:
            since_best += 1
            if since_best >= reset_every:  # reference: periodic reset to best
                cur, cur_cost = dict(best), best_cost
                since_best = 0
                if trace is not None:
                    trace.event("reset", iteration=it, best_cost=best_cost)
        if trace is not None:
            rec = {
                "iteration": it,
                "guid": g,
                "ops_changed": ops_changed,
                "delta": delta,
                "cur_cost": cur_cost,
            }
            if new_dp is not None:
                rec["dp"] = new_dp
                rec["ch"] = new_ch
            trace.candidate(
                kind, accepted=accepted, best_cost=best_cost, **rec
            )
        if verbose and it % max(budget // 10, 1) == 0:
            print(
                f"[mcmc] iter {it}: current {cur_cost * 1e3:.3f} ms, "
                f"best {best_cost * 1e3:.3f} ms"
            )
    anneal_cm.__exit__(None, None, None)
    if search.cm.measure:
        # one program launch per step (estimate_graph_cost's step_floor
        # basis) — keeps the cross-engine gate comparable
        best_cost += search.cm.dispatch_floor()
    result = UnityResult(best_cost, best)
    if trace is not None:
        search._trace_result(result, "mcmc")
    return result
