"""Repeated-block structure detection for pipeline parallelism.

The reference declares OP_PIPELINE but never implements it (ffconst.h:151);
our GPipe runtime (parallel/pipeline.py) needs the model expressed as
prologue → S identical blocks → epilogue with single-tensor boundaries.
This module detects that structure directly in the PCG, so the auto-search
can enumerate pipeline candidates (VERDICT r1 item 2) and compile() can
lower the winner without the user restructuring their model.

Detection:
  1. find *cut nodes*: positions in the topo order where exactly one
     tensor (the cut node's output 0) crosses into the suffix — the same
     single-entry boundary the reference's sequence splits use
     (find_split_node via post-dominators, substitution.cc:1984);
  2. slice the graph into segments between consecutive cuts;
  3. find the longest run of consecutive segments with identical
     signatures (op types + params + internal wiring), allowing a period
     of several segments per block (an attention+mlp transformer layer is
     3 single-node segments).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.core.types import OperatorType

# params that do not affect the computation's structure
_IGNORED_PARAMS = ("name", "initializers")


@dataclasses.dataclass
class BlockStructure:
    """prologue → blocks[0..S-1] (identical) → epilogue, chained through
    single-tensor boundaries."""

    prologue: List[int]  # guids, topo order (includes graph inputs)
    blocks: List[List[int]]  # S guid-lists, identical signatures
    epilogue: List[int]  # guids, topo order (may be empty)

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)


def _node_signature(node, pos_of_guid, seg_guids, prev_cut) -> Tuple:
    params = tuple(
        (k, repr(v))
        for k, v in sorted(node.params.items())
        if k not in _IGNORED_PARAMS
    )
    wiring = []
    for r in node.inputs:
        if r.guid in seg_guids:
            wiring.append(("internal", seg_guids[r.guid], r.out_idx))
        elif prev_cut is not None and r.guid == prev_cut:
            wiring.append(("boundary", r.out_idx))
        else:
            wiring.append(("external", r.guid, r.out_idx))
    return (node.op_type, params, tuple(wiring))


def find_block_structure(graph: PCGGraph) -> Optional[BlockStructure]:
    """Detect prologue → repeated blocks → epilogue; None when the graph
    has no repeated trunk of at least 2 blocks."""
    topo = graph.topo_order()
    n = len(topo)
    if n < 3:
        return None
    pos = {g: i for i, g in enumerate(topo)}

    # crossing refs per prefix boundary i: inputs of suffix nodes produced
    # in the prefix
    cuts: List[int] = []
    for i in range(n - 1):
        crossing = set()
        ok = True
        for v in topo[i + 1 :]:
            for r in graph.nodes[v].inputs:
                if pos.get(r.guid, n) <= i:
                    crossing.add((r.guid, r.out_idx))
                    if (r.guid, r.out_idx) != (topo[i], 0):
                        ok = False
            if not ok:
                break
        if ok and crossing == {(topo[i], 0)}:
            cuts.append(i)
    if len(cuts) < 3:
        return None

    # segments: seg[j] = topo[cuts[j-1]+1 .. cuts[j]] (ends AT its cut)
    segments: List[List[int]] = []
    seg_start = [c + 1 for c in [-1] + cuts[:-1]]
    for s, e in zip(seg_start, cuts):
        segments.append(topo[s : e + 1])

    # signatures
    sigs = []
    for j, seg in enumerate(segments):
        seg_guids = {g: k for k, g in enumerate(seg)}
        prev_cut = topo[cuts[j - 1]] if j > 0 else None
        sigs.append(
            tuple(
                _node_signature(graph.nodes[g], pos, seg_guids, prev_cut)
                for g in seg
            )
        )

    # inputs-only segments can't be blocks; find best (start, period, count)
    def is_trunk_seg(j):
        return all(
            graph.nodes[g].op_type != OperatorType.INPUT for g in segments[j]
        )

    m = len(segments)
    best = None  # (coverage, start, period, count)
    for period in range(1, m // 2 + 1):
        j = 0
        while j + 2 * period <= m:
            if not all(is_trunk_seg(j + t) for t in range(period)):
                j += 1
                continue
            count = 1
            while (
                j + (count + 1) * period <= m
                and sigs[j + count * period : j + (count + 1) * period]
                == sigs[j : j + period]
                and all(
                    is_trunk_seg(j + count * period + t)
                    for t in range(period)
                )
            ):
                count += 1
            if count >= 2:
                coverage = count * period
                cand = (coverage, j, period, count)
                if best is None or cand[0] > best[0]:
                    best = cand
                j += count * period
            else:
                j += 1
    if best is None:
        return None
    _, start, period, count = best

    blocks = [
        [g for t in range(period) for g in segments[start + k * period + t]]
        for k in range(count)
    ]
    prologue = [g for seg in segments[:start] for g in seg]
    epilogue = [
        g for seg in segments[start + count * period :] for g in seg
    ]
    # trailing nodes after the last cut (the final segment may not end at
    # a cut — e.g. the loss head)
    covered = set(prologue) | set(epilogue) | {
        g for blk in blocks for g in blk
    }
    epilogue += [g for g in topo if g not in covered]

    # every block must consume exactly the previous boundary; verify the
    # first block's external inputs are only the prologue's cut output
    first = blocks[0]
    first_set = set(first)
    entry = prologue[-1] if prologue else None
    for g in first:
        for r in graph.nodes[g].inputs:
            if r.guid not in first_set and r.guid != entry:
                return None
    return BlockStructure(prologue, blocks, epilogue)
