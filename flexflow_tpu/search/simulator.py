"""Graph-level step-time estimation for candidate parallel strategies.

The TPU rebuild of the reference's task-graph simulation
(reference: Simulator::simulate_runtime, src/runtime/simulator.cc:810-1240).
Two modes:

  * **taskgraph** (default): lower the annotated PCG into a SimTask DAG —
    forward/backward compute on a representative chip (one XLA stream;
    SPMD makes all chips symmetric), collectives and per-weight gradient
    all-reduces on an ICI link resource — and replay it event-driven
    through the native simulator (native/src/simulator.cc, pure-Python
    fallback inside flexflow_tpu.native). This captures what the analytic
    sum cannot: gradient syncs overlapping with the remaining backward
    compute, exactly the overlap XLA's async collectives give a real step.
  * **analytic**: the reference's `LogicalTaskgraphBasedSimulator` style
    closed-form sum (simulator.h:776-818) — compute + comm + sync.

Costs come from `CostModel`; parallel ops map to collectives per the
SURVEY §2.3 table:

  Replicate  fwd broadcast(free: GSPMD keeps unsharded axes replicated),
             bwd all-reduce of the grad over the replica group
  Reduction  fwd all-reduce of partial sums, bwd free
  Repartition/Combine/AllToAll  all-to-all / all-gather reshards
  weight update  all-reduce of each weight grad over the mesh axes the
             weight is replicated on (the reference's NCCL allreduce,
             optimizer_kernel.cu:88)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.search.cost_model import CostModel, OpCost


@dataclasses.dataclass
class GraphCost:
    step_time: float = 0.0
    compute_time: float = 0.0
    comm_time: float = 0.0
    sync_time: float = 0.0
    update_time: float = 0.0  # optimizer HBM traffic (CostModel.update_cost)
    memory_per_chip: int = 0

    def feasible(self, spec: MachineSpec) -> bool:
        return self.memory_per_chip <= spec.hbm_bytes


def _sparse_embedding_rows(graph: PCGGraph, guid: int):
    """Per-chip touched rows per step if this node takes the executor's
    sparse-embedding fast path, else None. Eligibility comes from the
    ONE shared tracer (core.pcg.trace_embedding_ids_input) the executor
    also uses, so search and runtime cannot diverge."""
    from flexflow_tpu.core.pcg import trace_embedding_ids_input

    ref = trace_embedding_ids_input(graph, guid)
    if ref is None:
        return None
    return graph.shape_of(ref).piece_volume()


def _sparse_rows_shard_group(graph: PCGGraph, guid: int) -> int:
    """How many distinct shards the touched-row stream is split into — the
    group every table replica must all-gather over before applying the
    scatter-update (CostModel.sparse_sync_cost). Equals the ids input's
    total sharding degree; 1 (no sync) when the ids are replicated."""
    from flexflow_tpu.core.pcg import trace_embedding_ids_input

    ref = trace_embedding_ids_input(graph, guid)
    if ref is None:
        return 1
    return graph.shape_of(ref).total_degree


def sparse_embedding_node_cost(graph, guid, node, cm):
    """OpCost for a SPARSE-eligible embedding (else None) — the ONE
    compute-pricing site for the fast path, shared by estimate_graph_cost
    and auto._pipeline_candidate (unity derives the same numbers through
    _sparse_embedding_time). The executor's fast path gathers/scatters
    touched rows only, so neither the measured dense-grad kernel nor the
    table-sized roofline applies (the round-4 DLRM 490x finding)."""
    if (
        not cm.sparse_embedding
        or node.op_type != OperatorType.EMBEDDING
        or not node.weight_shapes
    ):
        return None
    rows = _sparse_embedding_rows(graph, guid)
    if rows is None:
        return None
    f, b = cm.sparse_embedding_op_cost(node.weight_shapes[0], rows)
    mem = sum(cm.piece_bytes(s) for s in node.output_shapes)
    mem += sum(cm.piece_bytes(s) for s in node.weight_shapes)
    return OpCost(f, b, 0.0, int(mem))


def _group_size(shape, mesh_sizes) -> int:
    """Mesh axes a tensor is NOT sharded over = its replication group."""
    used = set()
    for d in shape.dims:
        if d.degree > 1 and d.parallel_idx >= 0:
            used.add(d.parallel_idx)
    group = 1
    for i, s in enumerate(mesh_sizes):
        if i not in used:
            group *= s
    return group


def _axis_group_chips(axis: int, degree: int, mesh_sizes) -> range:
    """Device ids of one collective group on a mesh axis. Devices are laid
    out row-major over the mesh, so an axis-i group strides by the product
    of the trailing axis sizes — the geometry a topology-aware machine
    model needs to price cross-node rings correctly."""
    stride = 1
    for s in mesh_sizes[axis + 1:]:
        stride *= s
    return range(0, degree * stride, stride)


def _parallel_op_comm(
    node, in_shapes, cm: CostModel, mesh_sizes=()
) -> Tuple[float, float]:
    """(fwd, bwd) collective seconds for one parallel op (SURVEY §2.3)."""
    x = in_shapes[0]
    y = node.output_shapes[0]
    axis = _collective_axis(node, mesh_sizes)

    _pb = cm.piece_bytes  # wire bytes honor dtype + bf16 mixed precision
    fwd = bwd = 0.0
    if node.op_type == OperatorType.REPLICATE:
        deg = node.params["degree"]
        bwd = cm.all_reduce(
            _pb(x), deg, chips=_axis_group_chips(axis, deg, mesh_sizes)
        )
    elif node.op_type == OperatorType.REDUCTION:
        deg = node.params["degree"]
        fwd = cm.all_reduce(
            _pb(y), deg, chips=_axis_group_chips(axis, deg, mesh_sizes)
        )
    elif node.op_type == OperatorType.REPARTITION:
        deg = node.params["degree"]
        chips = _axis_group_chips(axis, deg, mesh_sizes)
        fwd = cm.all_to_all(_pb(x), deg, chips=chips)
        bwd = cm.all_gather(_pb(y), deg, chips=chips)
    elif node.op_type == OperatorType.COMBINE:
        deg = node.params["degree"]
        chips = _axis_group_chips(axis, deg, mesh_sizes)
        fwd = cm.all_gather(_pb(x), deg, chips=chips)
        bwd = cm.all_to_all(_pb(y), deg, chips=chips)
    elif node.op_type in (OperatorType.ALLTOALL, OperatorType.FUSED_PARALLEL):
        deg = max(x.total_degree, y.total_degree)
        chips = _axis_group_chips(axis, deg, mesh_sizes)
        fwd = cm.all_to_all(_pb(x), deg, chips=chips)
        bwd = cm.all_to_all(_pb(y), deg, chips=chips)
    return fwd, bwd


_CHIP = 0  # compute resource id (one XLA stream per chip; SPMD-symmetric)


def _collective_axis(node, mesh_sizes) -> int:
    """Mesh axis a parallel op's collective rides. Collectives over
    different mesh axes use disjoint ICI torus dimensions and may overlap;
    same-axis collectives serialize on their link resource."""
    idx = node.params.get("parallel_idx", -1)
    if isinstance(idx, int) and 0 <= idx < len(mesh_sizes):
        return idx
    return len(mesh_sizes) - 1  # model axis by convention


def estimate_graph_cost(
    graph: PCGGraph,
    cost_model: CostModel,
    mesh_sizes,
    include_backward: bool = True,
    optimizer_state_factor: float = 3.0,
    mode: str = "taskgraph",
    export: Optional[Dict] = None,
    trace=None,
    trace_label: str = "",
) -> GraphCost:
    """Estimate one training-iteration time for an annotated PCG.

    optimizer_state_factor: weights + grads + momentum ≈ 3× weight bytes
    (Adam: 4×) — feeds the HBM feasibility check.

    export: when a dict is passed, it is filled with the SimTask arrays
    (taskgraph mode) AND a per-node ``node_costs`` list ({guid, name,
    op, family, forward, backward, memory}) — the breakdown the
    predicted-vs-measured audit (search/audit.py) groups by op family.

    trace: an optional telemetry.SearchTrace — records ONE candidate
    row carrying this estimate's full GraphCost breakdown (compute /
    comm / sync / update / memory feasibility), labeled `trace_label`.
    """
    cm = cost_model
    total = GraphCost()
    weight_bytes = 0
    act_bytes = 0
    taskgraph = mode != "analytic"
    # resource ids: chip 0, then one ICI link resource per mesh axis
    num_resources = 1 + max(1, len(mesh_sizes))

    def link(axis: int) -> int:
        return 1 + min(axis, num_resources - 2)

    # SimTask arrays (taskgraph mode)
    resource_of: List[int] = []
    duration: List[float] = []
    names: List[str] = []
    edges: List[Tuple[int, int]] = []
    fwd_task: Dict[int, int] = {}
    bwd_task: Dict[int, int] = {}
    bwd_comm: Dict[int, float] = {}

    def add_task(resource: int, dur: float, name: str = "") -> int:
        if not taskgraph:
            return -1
        resource_of.append(resource)
        duration.append(dur)
        names.append(name)
        return len(resource_of) - 1

    def add_edge(src: int, dst: int):
        if taskgraph:
            edges.append((src, dst))

    topo = graph.topo_order()

    # ---- fusion awareness (measured mode only) ------------------------------
    # Measured kernels are timed in ISOLATION (the reference's
    # inner_measure_operator_cost has the same structural bias,
    # model.cu:38-74): an elementwise op downstream of an MXU op costs a
    # full activation round-trip on its own, but XLA folds it into the
    # producer's epilogue in the real compiled step. Charging it again is
    # why ResNet over-predicted 1.8-2.3x (BASELINE.md round-2 residuals).
    # Under cm.measure, unary elementwise ops whose sole producer is an
    # MXU head (or an op already fused into one) are costed at zero;
    # binary elementwise (residual adds: the skip read is real traffic)
    # and batchnorm (its stats reduction survives fusion) at half.
    fused_free: set = set()
    fused_half: set = set()
    chain_cost: Dict[int, Tuple[float, float]] = {}  # head guid -> (fwd, bwd)
    if cm.measure:
        from flexflow_tpu.search.cost_model import _MXU_OPS

        _free_types = {
            OperatorType.RELU,
            OperatorType.SIGMOID,
            OperatorType.TANH,
            OperatorType.ELU,
            OperatorType.GELU,
            OperatorType.IDENTITY,
            OperatorType.EXP,
            OperatorType.SIN,
            OperatorType.COS,
            OperatorType.POW,
            OperatorType.RSQRT,
            OperatorType.SCALAR_MULTIPLY,
            OperatorType.SCALAR_ADD,
            OperatorType.SCALAR_SUB,
            OperatorType.SCALAR_TRUE_DIV,
            OperatorType.CAST,
            OperatorType.DROPOUT,
        }
        _half_types = {
            OperatorType.EW_ADD,
            OperatorType.EW_SUB,
            OperatorType.EW_MUL,
            OperatorType.EW_DIV,
            OperatorType.EW_MAX,
            OperatorType.EW_MIN,
            OperatorType.BATCHNORM,
            OperatorType.LAYERNORM,
            OperatorType.SOFTMAX,
        }
        _fusable = _free_types | _half_types
        for guid in topo:
            node = graph.nodes[guid]
            if node.op_type not in _fusable:
                continue
            if not any(
                graph.nodes[r.guid].op_type in _MXU_OPS
                or r.guid in fused_free
                or r.guid in fused_half
                for r in node.inputs
            ):
                continue
            if node.op_type in _free_types:
                fused_free.add(guid)
            else:
                fused_half.add(guid)

        # Measure epilogue CHAINS as one kernel where possible (round-3
        # attack on the conv residual: isolated conv + the half-for-bn
        # heuristic left ResNet at 1.40 pred/meas — timing conv→bn→relu
        # together measures what XLA actually compiles). A successful
        # chain measurement replaces the head's cost and zeroes the chain
        # members; failures keep the free/half heuristics above.
        for guid in topo:
            node = graph.nodes[guid]
            if node.op_type not in _MXU_OPS:
                continue
            chain = []
            cur = guid
            while True:
                consumers = list(graph.consumers(cur))
                if len(consumers) != 1:
                    break
                nxt = consumers[0]
                nnode = graph.nodes[nxt]
                if nnode.op_type not in _fusable:
                    break
                if len(nnode.inputs) > 1:
                    # residual adds read a second real activation — that
                    # traffic is not epilogue-free; stop the chain (the
                    # half heuristic above still applies to them)
                    break
                chain.append(nxt)
                cur = nxt
            if not chain:
                continue
            # chain members are single-input by construction, so the
            # chained input index is always 0
            head_ins = [graph.shape_of(r) for r in node.inputs]
            specs = [
                (node.op_type, node.params, head_ins, node.weight_shapes, 0)
            ]
            for g2 in chain:
                n2 = graph.nodes[g2]
                specs.append(
                    (
                        n2.op_type,
                        n2.params,
                        [graph.shape_of(r) for r in n2.inputs],
                        n2.weight_shapes,
                        0,
                    )
                )
            from flexflow_tpu.search.cost_model import shard_batch as _sb

            mt = cm.corrected_times(
                node.op_type, cm.chain_times_floor_adjusted(specs),
                batch=_sb(head_ins),
            )
            if mt is None:
                continue
            chain_cost[guid] = mt
            fused_free.update(chain)
            fused_half.difference_update(chain)

    # ---- forward pass -------------------------------------------------------
    per_node_cost: Dict[int, OpCost] = {}
    for guid in topo:
        node = graph.nodes[guid]
        in_shapes = [graph.shape_of(r) for r in node.inputs]

        if node.op_type == OperatorType.INPUT:
            # stored at true dtype: mixed precision downcasts matmul
            # operands on the fly, not residents (ops/registry.mm_operands)
            act_bytes += sum(s.piece_bytes() for s in node.output_shapes)
            t = add_task(_CHIP, 0.0, f"{node.name}.in")
        elif node.is_parallel_op:
            f, b = _parallel_op_comm(node, in_shapes, cm, mesh_sizes)
            total.comm_time += f + (b if include_backward else 0.0)
            per_node_cost[guid] = OpCost(0.0, 0.0, 0.0, 0)
            t = add_task(
                link(_collective_axis(node, mesh_sizes)), f, f"{node.name}.fwd"
            )
            bwd_comm[guid] = b
        else:
            cost = sparse_embedding_node_cost(graph, guid, node, cm)
            if cost is None:
                # a chain-measured head must not ALSO pay the isolated
                # kernel measurement it would immediately discard
                cost = cm.op_cost(
                    node, in_shapes, skip_measure=guid in chain_cost
                )
            if guid in chain_cost:
                # measured as one fused epilogue chain (the chain's
                # members are in fused_free)
                f, b = chain_cost[guid]
                cost = OpCost(f, b, 0.0, cost.memory)
            if guid in fused_free:
                cost = OpCost(0.0, 0.0, 0.0, cost.memory)
            elif guid in fused_half:
                cost = OpCost(
                    0.5 * cost.forward_time,
                    0.5 * cost.backward_time,
                    0.0,
                    cost.memory,
                )
            per_node_cost[guid] = cost
            total.compute_time += cost.forward_time
            if include_backward:
                total.compute_time += cost.backward_time
            act_bytes += sum(s.piece_bytes() for s in node.output_shapes)
            t = add_task(_CHIP, cost.forward_time, f"{node.name}.fwd")
        fwd_task[guid] = t
        for r in node.inputs:
            if r.guid in fwd_task:
                add_edge(fwd_task[r.guid], t)

    # ---- backward pass ------------------------------------------------------
    if include_backward:
        for guid in reversed(topo):
            node = graph.nodes[guid]
            if node.op_type == OperatorType.INPUT:
                continue
            if node.is_parallel_op:
                t = add_task(
                    link(_collective_axis(node, mesh_sizes)),
                    bwd_comm.get(guid, 0.0),
                    f"{node.name}.bwd",
                )
            else:
                t = add_task(
                    _CHIP, per_node_cost[guid].backward_time, f"{node.name}.bwd"
                )
            bwd_task[guid] = t
            add_edge(fwd_task[guid], t)  # bwd after own fwd
            for c in graph.consumers(guid):
                if c in bwd_task:
                    add_edge(bwd_task[c], t)

    # ---- gradient sync (per-weight all-reduce over replication group) -------
    # Grad all-reduces ride the data axis (axis 0): TP-sharded weights are
    # replicated over "data", DP-replicated weights reduce over it.
    for guid in topo:
        node = graph.nodes[guid]
        if not node.weight_shapes:
            continue
        t_sync = 0.0
        t_update = 0.0
        total_chips = 1
        for s in mesh_sizes:
            total_chips *= s
        sparse_rows = (
            _sparse_embedding_rows(graph, guid)
            if cm.sparse_embedding
            else None
        )
        sparse_group = (
            _sparse_rows_shard_group(graph, guid)
            if sparse_rows is not None
            else 1
        )
        for w in node.weight_shapes:
            weight_bytes += w.piece_bytes()
            if include_backward:
                if sparse_rows is not None:
                    # sparse fast path (Executor._sparse_embedding_guids):
                    # no table-sized gradient ever materializes — no
                    # table all-reduce, and the update walks only the
                    # touched rows (the measured 587x DLRM win)
                    t_update += cm.sparse_update_cost(
                        w, sparse_rows, optimizer_state_factor
                    )
                    # replicas must still see each other's touched rows:
                    # batch-sharded ids scattering into a shared table cost
                    # an all-gather of rows x dim over the id shards
                    sg = sparse_group
                    if sg > 1:
                        row_b = (
                            sparse_rows
                            * w.dims[-1].piece_size
                            * w.dtype.size_bytes
                        )
                        chips = (
                            range(total_chips)
                            if sg >= total_chips
                            else _axis_group_chips(0, sg, mesh_sizes)
                        )
                        t_sync += cm.sparse_sync_cost(row_b, sg, chips=chips)
                    continue
                g = _group_size(w, mesh_sizes)
                chips = (
                    range(total_chips)
                    if g >= total_chips
                    else _axis_group_chips(0, g, mesh_sizes)
                )
                t_sync += cm.all_reduce(cm.piece_bytes(w), g, chips=chips)
                t_update += cm.update_cost(w, optimizer_state_factor)
        t = None
        if include_backward and t_sync > 0:
            total.sync_time += t_sync
            t = add_task(link(0), t_sync, f"{node.name}.sync")
            add_edge(bwd_task.get(guid, fwd_task[guid]), t)
        if include_backward and t_update > 0:
            # the update consumes the synced grad: a chip-resource task
            # after both the bwd compute and the sync (reference: per-
            # parameter SGD/ADAM_UPD tasks, optimizer_kernel.cu:88)
            total.update_time += t_update
            tu = add_task(_CHIP, t_update, f"{node.name}.update")
            add_edge(bwd_task.get(guid, fwd_task[guid]), tu)
            if t is not None:
                add_edge(t, tu)

    total.memory_per_chip = int(weight_bytes * optimizer_state_factor + act_bytes)

    if export is not None:
        # per-node predicted compute costs keyed for the audit's
        # family grouping (cost_model.op_family); parallel ops carry
        # zero compute and are omitted — their traffic is the comm_time
        # aggregate above
        from flexflow_tpu.search.cost_model import op_family

        export["node_costs"] = [
            {
                "guid": guid,
                "name": graph.nodes[guid].name,
                "op": graph.nodes[guid].op_type.name,
                "family": op_family(graph.nodes[guid].op_type) or "other",
                "forward": per_node_cost[guid].forward_time,
                "backward": per_node_cost[guid].backward_time,
                "memory": per_node_cost[guid].memory,
            }
            for guid in topo
            if guid in per_node_cost
            and not graph.nodes[guid].is_parallel_op
        ]

    def _traced(result: GraphCost) -> GraphCost:
        if trace is not None:
            # scalars only — the GraphCost is rebuilt per candidate, but
            # the discipline (FX104) is uniform: no live state in rows
            trace.candidate(
                "graph_cost",
                name=trace_label or "estimate_graph_cost",
                step_time=result.step_time,
                compute_time=result.compute_time,
                comm_time=result.comm_time,
                sync_time=result.sync_time,
                update_time=result.update_time,
                memory_per_chip=float(result.memory_per_chip),
                feasible=bool(result.feasible(cm.spec)),
            )
        return result

    # the real train step is ONE XLA program and pays one program launch
    # — the same overhead CostModel.dispatch_floor measures and subtracts
    # per-op. Invisible for ms-scale steps; for DLRM-class us-scale steps
    # it IS most of the wall time (the round-5 rank gate read predicted
    # 4 us vs measured 26 us before this term). Applied in BOTH modes and
    # mirrored by every other step-time producer (auto._pipeline_candidate,
    # unity/mcmc totals) so cross-engine comparisons stay on one basis.
    step_floor = cm.dispatch_floor() if cm.measure else 0.0

    if not taskgraph:
        total.step_time = (
            total.compute_time
            + total.comm_time
            + total.sync_time
            + total.update_time
            + step_floor
        )
        return _traced(total)

    if export is not None:
        export.update(
            resource_of=list(resource_of),
            duration=list(duration),
            names=list(names),
            edges=list(edges),
            num_resources=num_resources,
        )

    from flexflow_tpu import native

    sim = native.simulate(resource_of, duration, edges, num_resources)
    if sim is None:  # malformed candidate graph — treat as analytic
        total.step_time = (
            total.compute_time
            + total.comm_time
            + total.sync_time
            + total.update_time
        )
    else:
        total.step_time = sim[0]
    total.step_time += step_floor
    return _traced(total)
