"""Graph-level step-time estimation for candidate parallel strategies.

The TPU rebuild of the reference's task-graph simulation
(reference: Simulator::simulate_runtime, src/runtime/simulator.cc:810-1240).
The reference replays an event-driven SimTask DAG over a machine model; under
XLA one jitted step has no per-task launch overheads and collectives are the
only explicit communication, so v1 models a step as

    sum over ops(max(roofline compute)) + sum(collective times) + grad sync

i.e. the reference's `LogicalTaskgraphBasedSimulator` analytic mode
(simulator.h:776-818) rather than the full event replay. Costs come from
`CostModel`; parallel ops map to collectives per the §2.3 table:

  Replicate  fwd broadcast(free: GSPMD keeps unsharded axes replicated),
             bwd all-reduce of the grad over the replica group
  Reduction  fwd all-reduce of partial sums, bwd free
  Repartition/Combine/AllToAll  all-to-all / all-gather reshards
  weight update  all-reduce of each weight grad over the mesh axes the
             weight is replicated on (the reference's NCCL allreduce,
             optimizer_kernel.cu:88)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from flexflow_tpu.core.machine import MachineSpec
from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.core.types import OperatorType
from flexflow_tpu.search.cost_model import CostModel, OpCost


@dataclasses.dataclass
class GraphCost:
    step_time: float = 0.0
    compute_time: float = 0.0
    comm_time: float = 0.0
    sync_time: float = 0.0
    memory_per_chip: int = 0

    def feasible(self, spec: MachineSpec) -> bool:
        return self.memory_per_chip <= spec.hbm_bytes


def _group_size(shape, mesh_sizes) -> int:
    """Mesh axes a tensor is NOT sharded over = its replication group."""
    used = set()
    for d in shape.dims:
        if d.degree > 1 and d.parallel_idx >= 0:
            used.add(d.parallel_idx)
    group = 1
    for i, s in enumerate(mesh_sizes):
        if i not in used:
            group *= s
    return group


def estimate_graph_cost(
    graph: PCGGraph,
    cost_model: CostModel,
    mesh_sizes,
    include_backward: bool = True,
    optimizer_state_factor: float = 3.0,
) -> GraphCost:
    """Estimate one training-iteration time for an annotated PCG.

    optimizer_state_factor: weights + grads + momentum ≈ 3× weight bytes
    (Adam: 4×) — feeds the HBM feasibility check.
    """
    total = GraphCost()
    weight_bytes = 0
    act_bytes = 0
    cm = cost_model

    for guid in graph.topo_order():
        node = graph.nodes[guid]
        in_shapes = [graph.shape_of(r) for r in node.inputs]

        if node.op_type == OperatorType.INPUT:
            act_bytes += sum(s.piece_bytes() for s in node.output_shapes)
            continue

        if node.is_parallel_op:
            x = in_shapes[0]
            y = node.output_shapes[0]
            t = 0.0
            if node.op_type == OperatorType.REPLICATE:
                deg = node.params["degree"]
                if include_backward:
                    t += cm.all_reduce(x.piece_bytes(), deg)
            elif node.op_type == OperatorType.REDUCTION:
                deg = node.params["degree"]
                t += cm.all_reduce(y.piece_bytes(), deg)
            elif node.op_type == OperatorType.REPARTITION:
                deg = node.params["degree"]
                t += cm.all_to_all(x.piece_bytes(), deg)
                if include_backward:
                    t += cm.all_gather(y.piece_bytes(), deg)
            elif node.op_type == OperatorType.COMBINE:
                deg = node.params["degree"]
                t += cm.all_gather(x.piece_bytes(), deg)
                if include_backward:
                    t += cm.all_to_all(y.piece_bytes(), deg)
            elif node.op_type in (
                OperatorType.ALLTOALL,
                OperatorType.FUSED_PARALLEL,
            ):
                deg = max(x.total_degree, y.total_degree)
                t += cm.all_to_all(x.piece_bytes(), deg)
                if include_backward:
                    t += cm.all_to_all(y.piece_bytes(), deg)
            total.comm_time += t
            continue

        cost = cm.op_cost(node, in_shapes)
        total.compute_time += cost.forward_time
        if include_backward:
            total.compute_time += cost.backward_time
        act_bytes += sum(s.piece_bytes() for s in node.output_shapes)

        # gradient sync per weight (reference: per-parameter NCCL allreduce)
        for w in node.weight_shapes:
            weight_bytes += w.piece_bytes()
            if include_backward:
                g = _group_size(w, mesh_sizes)
                total.sync_time += cm.all_reduce(w.piece_bytes(), g)

    total.memory_per_chip = int(
        weight_bytes * optimizer_state_factor + act_bytes
    )
    total.step_time = total.compute_time + total.comm_time + total.sync_time
    return total
