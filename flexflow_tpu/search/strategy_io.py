"""Strategy import/export.

Rebuild of the reference's strategy file I/O (reference:
src/runtime/strategy.cc:100-197 load/save of per-op ParallelConfig maps,
exposed as --export-strategy / --import-strategy). The on-disk format is
JSON instead of the reference's binary protobuf: the global mesh plus the
enabled rewrite sites, keyed by op *names* (stable across runs of the same
builder program, like the reference's per-op keys).
"""

from __future__ import annotations

import json
from typing import Dict, List

from flexflow_tpu.core.pcg import PCGGraph
from flexflow_tpu.parallel.strategy import Strategy, data_parallel_strategy

_SITE_KINDS = {}


def _register_site_kinds():
    from flexflow_tpu.search.rewrites import (
        AttentionSite,
        ConvChannelSite,
        EmbeddingSite,
        ExpertParallelSite,
        LinearChainSite,
        SingleLinearSite,
    )

    _SITE_KINDS.update(
        {
            "attention": AttentionSite,
            "conv_channel": ConvChannelSite,
            "embedding": EmbeddingSite,
            "expert_parallel": ExpertParallelSite,
            "linear_chain": LinearChainSite,
            "single_linear": SingleLinearSite,
        }
    )


def save_search_result(result, graph: PCGGraph, path: str):
    """Persist a SearchResult (search.auto) for later --import-strategy."""
    sites = []
    for site, enabled in zip(result.sites, result.on):
        if enabled:
            sites.append(
                {
                    "kind": site.kind,
                    "names": [graph.nodes[g].name for g in site.guids],
                }
            )
    doc = {
        "version": 1,
        "kind": getattr(result, "kind", "tp"),
        "dp": result.dp,
        "tp": result.tp,
        "extra": getattr(result, "extra", {}),
        "simulated_step_ms": result.cost.step_time * 1e3,
        "sites": sites,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)


def save_strategy(strategy: Strategy, path: str):
    """Persist a plain Strategy (mesh only; site-level detail requires a
    SearchResult — use save_search_result from the search path)."""
    doc = {
        "version": 1,
        "mesh_axes": list(strategy.mesh_config.axis_names),
        "mesh_sizes": list(strategy.mesh_config.axis_sizes),
        "name": strategy.name,
        "sites": [],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)


def _check_second_axis_shards(strategy, graph: PCGGraph, deg: int, path: str):
    """An imported (dp x <axis>) strategy whose second axis shards NOTHING
    on this graph would silently idle those chips — the search path has
    this exact guard (auto._second_axis_candidate); imports need it too."""
    from flexflow_tpu.runtime.executor import propagate_shapes

    g = graph.copy()
    strategy.apply(g)
    propagate_shapes(g)
    from flexflow_tpu.core.types import OperatorType

    if not any(
        d.degree == deg and d.parallel_idx == 1
        for n in g.nodes.values()
        if n.op_type == OperatorType.INPUT
        for d in n.output_shapes[0].dims
    ):
        raise ValueError(
            f"strategy file {path!r}: the second mesh axis (degree {deg}) "
            "shards no input of this graph — the strategy does not apply"
        )


def load_strategy(path: str, graph: PCGGraph, num_devices: int) -> Strategy:
    """Rebuild a Strategy from JSON against the current graph
    (reference: load_strategies_from_file + compile-time map lookup)."""
    _register_site_kinds()
    with open(path) as f:
        doc = json.load(f)

    dp = int(doc.get("dp", doc.get("mesh_sizes", [num_devices])[0]))
    tp = int(doc.get("tp", 1))
    kind = doc.get("kind", "tp")
    extra = doc.get("extra", {})

    if kind == "seq":
        from flexflow_tpu.parallel.strategy import sequence_parallel_strategy

        sp = int(extra.get("sp", 1))
        if dp * sp > num_devices:
            raise ValueError(
                f"strategy file wants {dp * sp} devices, have {num_devices}"
            )
        s = sequence_parallel_strategy(
            dp, sp, graph, seq_mode=extra.get("seq_mode", "ring")
        )
        if sp > 1:
            _check_second_axis_shards(s, graph, sp, path)
        s.name = f"imported:{path}"
        return s
    if kind == "spatial":
        from flexflow_tpu.parallel.strategy import spatial_parallel_strategy

        hp = int(extra.get("hp", 1))
        if dp * hp > num_devices:
            raise ValueError(
                f"strategy file wants {dp * hp} devices, have {num_devices}"
            )
        s = spatial_parallel_strategy(dp, hp, graph)
        if hp > 1:
            _check_second_axis_shards(s, graph, hp, path)
        s.name = f"imported:{path}"
        return s
    if kind == "pipeline":
        from flexflow_tpu.parallel.strategy import pipeline_strategy

        pp = int(extra.get("pp", 1))
        if dp * pp > num_devices:
            raise ValueError(
                f"strategy file wants {dp * pp} devices, have {num_devices}"
            )
        return pipeline_strategy(
            graph,
            dp,
            pp,
            num_microbatches=int(extra.get("mb", 4)),
            schedule=extra.get("schedule", "gpipe"),
            name_prefix=f"imported:{path}",
        )

    if dp * tp > num_devices:
        raise ValueError(
            f"strategy file wants {dp * tp} devices, have {num_devices}"
        )
    if tp <= 1 and not doc.get("sites"):
        # respect the saved dp (an idle-chip dp is a deliberate choice)
        return data_parallel_strategy(dp or num_devices, graph)

    name_to_guid: Dict[str, int] = {
        n.name: g for g, n in graph.nodes.items()
    }
    sites = []
    for entry in doc.get("sites", []):
        cls = _SITE_KINDS.get(entry["kind"])
        if cls is None:
            raise ValueError(f"unknown site kind {entry['kind']!r}")
        try:
            guids = tuple(name_to_guid[nm] for nm in entry["names"])
        except KeyError as e:
            raise ValueError(
                f"strategy file references unknown op {e.args[0]!r}"
            ) from None
        sites.append(cls(entry["kind"], guids))

    if kind == "mixed":
        # heterogeneous lowering: TP sites + full-width dp outside them
        # (falling through to the uniform path would silently import a
        # DIFFERENT strategy than was exported)
        from flexflow_tpu.parallel.strategy import mixed_site_strategy

        if dp * tp > num_devices:
            raise ValueError(
                f"mixed strategy file wants {dp * tp} devices, "
                f"have {num_devices}"
            )
        # honor the FILE's device count (like the seq/spatial import
        # paths): importing on a wider machine must not silently widen
        # the data axis into a different strategy than was exported
        s = mixed_site_strategy(
            graph, dp * tp, tp, sites, name_prefix=f"imported:{path}"
        )
        if "mixed" not in s.name:
            raise ValueError(
                f"strategy file {path!r} is a mixed strategy but the "
                "current graph/device count cannot express it"
            )
        return s

    from flexflow_tpu.runtime.executor import MeshConfig
    from flexflow_tpu.search.auto import _MODEL_AXIS, _annotate_data_parallel

    mesh = (
        MeshConfig(("data", "model"), (dp, tp))
        if tp > 1
        else MeshConfig(("data",), (dp,))
    )

    def apply(g: PCGGraph):
        _annotate_data_parallel(g, dp)
        for site in sites:
            site.apply(g, tp, _MODEL_AXIS)

    return Strategy(mesh, apply, name=f"imported:{path}")
