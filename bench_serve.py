"""Serving benchmark driver: continuous vs static batching throughput.

Prints ONE JSON line in the bench.py protocol ({"metric", "value",
"unit", "vs_baseline"} — extra serve-specific keys ride along):
`value` is continuous-batching decode throughput in tokens/s and
`vs_baseline` is the ratio over STATIC batching of the identical
mixed-length request stream on the identical engine — the Orca win this
subsystem exists for, so the baseline is the pre-Orca scheduler, not a
training number. p50/p95 are per-request submit→finish latencies under
continuous batching.

The default workload is the flagship Transformer geometry (12 layers,
hidden 1024, 16 heads — transformer.cc:79-85) recast as a decoder LM;
`--smoke` shrinks it for CPU CI.
"""

from __future__ import annotations

import json
import sys


def run(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
):
    import jax

    from flexflow_tpu import (
        DataType,
        FFConfig,
        FFModel,
        LossType,
        SGDOptimizer,
    )
    from flexflow_tpu.models import build_decoder_lm
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        Request,
        ServeConfig,
        StaticBatchingScheduler,
        build_scheduler,
        latency_percentiles,
    )

    cfg = FFConfig(batch_size=max_seqs)
    model = FFModel(cfg)
    tok = model.create_tensor(
        [max_seqs, max_len], dtype=DataType.INT32, name="tokens"
    )
    build_decoder_lm(
        model,
        tok,
        vocab_size=vocab,
        hidden=hidden,
        num_heads=heads,
        num_layers=layers,
        ff_dim=4 * hidden,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
        devices=jax.devices()[:1],
    )

    def requests():
        # mixed-length stream: short and long continuations interleaved,
        # the regime where request-level batching strands slots
        short, long_ = max(2, max_len // 16), max(8, max_len // 2 - 8)
        return [
            Request(
                rid=i,
                prompt=[(i * 7 + j) % vocab for j in range(1 + i % 6)],
                max_new_tokens=short if i % 2 == 0 else long_,
            )
            for i in range(num_requests)
        ]

    serve = ServeConfig(max_seqs=max_seqs, max_seq_len=max_len)
    _, engine, _ = build_scheduler(model, serve)
    for cls in (ContinuousBatchingScheduler, StaticBatchingScheduler):
        cls(engine).run(requests()[: max_seqs + 1])  # warm jit signatures

    best = {}
    latencies = None
    for name, cls in (
        ("static", StaticBatchingScheduler),
        ("continuous", ContinuousBatchingScheduler),
    ):
        runs = []
        for _ in range(reps):
            sched = cls(engine)
            done = sched.run(requests())
            runs.append(sched.stats)
            if name == "continuous":
                latencies = latency_percentiles(done, (50, 95))
        best[name] = max(s.tokens_per_s for s in runs)

    return {
        "metric": (
            f"serve_decoder_{layers}L_{hidden}h_continuous_throughput"
        ),
        "value": round(best["continuous"], 2),
        "unit": "tokens/s",
        # ratio over static batching of the same stream (>1 = Orca win)
        "vs_baseline": round(best["continuous"] / best["static"], 3),
        "static_tokens_per_s": round(best["static"], 2),
        "p50_latency_ms": round(latencies[50] * 1e3, 2),
        "p95_latency_ms": round(latencies[95] * 1e3, 2),
    }


_PRESETS = {
    # flagship geometry (transformer.cc:79-85) as a decoder LM — the TPU
    # target; CPU CI uses --smoke
    "flagship": dict(
        layers=12, hidden=1024, heads=16, vocab=32000,
        max_seqs=8, max_len=512, num_requests=32,
    ),
    # mid-size config a CPU box can measure in minutes — the recorded
    # BENCH_SERVE.json numbers come from here when no TPU is attached
    "medium": dict(
        layers=4, hidden=256, heads=8, vocab=2048,
        max_seqs=4, max_len=128, num_requests=16,
    ),
    "smoke": dict(
        layers=2, hidden=64, heads=4, vocab=128,
        max_seqs=4, max_len=64, num_requests=8,
    ),
}


def main():
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    args = dict(_PRESETS["flagship"])
    argv = sys.argv[1:]
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--smoke":
            args = dict(_PRESETS["smoke"])
        elif a == "--preset":
            i += 1
            args = dict(_PRESETS[argv[i]])
        elif a.startswith("--") and a[2:].replace("-", "_") in args:
            i += 1
            args[a[2:].replace("-", "_")] = int(argv[i])
        else:
            raise SystemExit(f"unknown flag {a!r}")
        i += 1
    print(json.dumps(run(**args)))


if __name__ == "__main__":
    main()
